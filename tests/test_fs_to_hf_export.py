"""fs→HF export round-trips for the generic inverter families
(VERDICT r4 missing #3; reference merge-back analog:
fengshen/utils/llama_convert/fs_to_hf.py, merge_lt_mp_to_hf.py).

Two properties per family:
  1. export(import(state)) == state for EVERY key — keys the importer
     reads must round-trip bit-exactly; keys it never reads must keep
     their template values.
  2. a perturbed (="finetuned") flax tree survives export → re-import
     unchanged, so the export really carries the flax values and does
     not just echo the template.
"""

import numpy as np
import pytest

import jax

torch = pytest.importorskip("torch")


def _bart():
    import transformers

    from fengshen_tpu.models.bart.modeling_bart import BartConfig
    from fengshen_tpu.models.bart import convert

    hf_cfg = transformers.BartConfig(
        vocab_size=128, d_model=32, encoder_layers=2, decoder_layers=2,
        encoder_attention_heads=4, decoder_attention_heads=4,
        encoder_ffn_dim=64, decoder_ffn_dim=64,
        max_position_embeddings=64, attn_implementation="eager")
    torch.manual_seed(0)
    tm = transformers.BartForConditionalGeneration(hf_cfg).eval()
    cfg = BartConfig(vocab_size=128, d_model=32, encoder_layers=2,
                     decoder_layers=2, encoder_attention_heads=4,
                     decoder_attention_heads=4, encoder_ffn_dim=64,
                     decoder_ffn_dim=64, max_position_embeddings=64,
                     dtype="float32")
    return convert, tm.state_dict(), cfg, {}


def _pegasus():
    import transformers

    from fengshen_tpu.models.pegasus import PegasusConfig
    from fengshen_tpu.models.pegasus import convert

    hf_cfg = transformers.PegasusConfig(
        vocab_size=120, d_model=32, encoder_layers=2, decoder_layers=2,
        encoder_attention_heads=4, decoder_attention_heads=4,
        encoder_ffn_dim=64, decoder_ffn_dim=64,
        max_position_embeddings=64, activation_function="relu",
        scale_embedding=False)
    torch.manual_seed(0)
    tm = transformers.PegasusForConditionalGeneration(hf_cfg).eval()
    cfg = PegasusConfig(vocab_size=120, d_model=32, encoder_layers=2,
                        decoder_layers=2, encoder_attention_heads=4,
                        decoder_attention_heads=4, encoder_ffn_dim=64,
                        decoder_ffn_dim=64, max_position_embeddings=64,
                        activation_function="relu", scale_embedding=False,
                        dtype="float32")
    return convert, tm.state_dict(), cfg, {}


def _deberta():
    import transformers

    from fengshen_tpu.models.deberta_v2 import DebertaV2Config
    from fengshen_tpu.models.deberta_v2 import convert

    hf_cfg = transformers.DebertaV2Config(
        vocab_size=128, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, relative_attention=True,
        position_buckets=8, norm_rel_ebd="layer_norm", share_att_key=True,
        pos_att_type=["p2c", "c2p"], position_biased_input=False,
        attn_implementation="eager")
    torch.manual_seed(0)
    tm = transformers.DebertaV2Model(hf_cfg).eval()
    cfg = DebertaV2Config(
        vocab_size=128, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, position_buckets=8, dtype="float32")
    state = {f"deberta.{k}": v for k, v in tm.state_dict().items()}
    return convert, state, cfg, {}


def _roformer():
    import transformers

    from fengshen_tpu.models.roformer import RoFormerConfig
    from fengshen_tpu.models.roformer import convert

    hf_cfg = transformers.RoFormerConfig(
        vocab_size=128, embedding_size=32, hidden_size=32,
        num_hidden_layers=2, num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, rotary_value=False,
        attn_implementation="eager")
    torch.manual_seed(0)
    tm = transformers.RoFormerModel(hf_cfg).eval()
    cfg = RoFormerConfig(vocab_size=128, hidden_size=32,
                         num_hidden_layers=2, num_attention_heads=4,
                         intermediate_size=64, max_position_embeddings=64,
                         dtype="float32")
    state = {f"roformer.{k}": v for k, v in tm.state_dict().items()}
    return convert, state, cfg, {}


def _longformer():
    import transformers

    from fengshen_tpu.models.longformer.modeling_longformer import (
        LongformerConfig)
    from fengshen_tpu.models.longformer import convert

    hf_cfg = transformers.LongformerConfig(
        vocab_size=120, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=66, attention_window=[8, 8],
        pad_token_id=0)
    torch.manual_seed(0)
    tm = transformers.LongformerModel(hf_cfg, add_pooling_layer=False).eval()
    cfg = LongformerConfig(
        vocab_size=120, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, attention_window=8, dtype="float32")
    state = {f"longformer.{k}": v for k, v in tm.state_dict().items()}
    return convert, state, cfg, {}


def _albert():
    import transformers

    from fengshen_tpu.models.albert import AlbertConfig
    from fengshen_tpu.models.albert import convert

    hf_cfg = transformers.AlbertConfig(
        vocab_size=128, embedding_size=16, hidden_size=32,
        num_hidden_layers=3, num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, attn_implementation="eager")
    torch.manual_seed(0)
    tm = transformers.AlbertModel(hf_cfg).eval()
    cfg = AlbertConfig(vocab_size=128, embedding_size=16, hidden_size=32,
                       num_hidden_layers=3, num_attention_heads=4,
                       intermediate_size=64, max_position_embeddings=64,
                       dtype="float32")
    state = {f"albert.{k}": v for k, v in tm.state_dict().items()}
    return convert, state, cfg, {}


def _deltalm():
    from fengshen_tpu.models.deltalm import DeltaLMConfig
    from fengshen_tpu.models.deltalm import convert

    cfg = DeltaLMConfig.small_test_config()
    d, f = cfg.d_model, cfg.encoder_ffn_dim
    shapes = {"encoder.embed_tokens.weight": (cfg.vocab_size, d),
              "encoder.embed_positions.weight": (
                  cfg.max_position_embeddings + 2, d)}
    for pre, n in (("encoder", cfg.encoder_layers),
                   ("decoder", cfg.decoder_layers)):
        shapes[f"{pre}.layernorm_embedding.weight"] = (d,)
        shapes[f"{pre}.layernorm_embedding.bias"] = (d,)
        shapes[f"{pre}.layer_norm.weight"] = (d,)
        shapes[f"{pre}.layer_norm.bias"] = (d,)
        for i in range(n):
            p = f"{pre}.layers.{i}"
            for att in (["self_attn"] if pre == "encoder" else
                        ["self_attn", "encoder_attn"]):
                for proj in ("q_proj", "k_proj", "v_proj", "out_proj"):
                    shapes[f"{p}.{att}.{proj}.weight"] = (d, d)
                    shapes[f"{p}.{att}.{proj}.bias"] = (d,)
                shapes[f"{p}.{att}_layer_norm.weight"] = (d,)
                shapes[f"{p}.{att}_layer_norm.bias"] = (d,)
            fcs = ("fc1", "fc2") if pre == "encoder" else \
                ("fc1", "fc2", "fc3", "fc4")
            for fc in fcs:
                wide = fc in ("fc1", "fc3")
                shapes[f"{p}.{fc}.weight"] = (f, d) if wide else (d, f)
                shapes[f"{p}.{fc}.bias"] = (f,) if wide else (d,)
            shapes[f"{p}.final_layer_norm.weight"] = (d,)
            shapes[f"{p}.final_layer_norm.bias"] = (d,)
            if pre == "decoder":
                shapes[f"{p}.ffn_layer_norm.weight"] = (d,)
                shapes[f"{p}.ffn_layer_norm.bias"] = (d,)
    rng = np.random.RandomState(7)
    state = {k: rng.randn(*s).astype(np.float32) for k, s in shapes.items()}
    return convert, state, cfg, {}


def _gpt2():
    import transformers

    from fengshen_tpu.models.gpt2 import GPT2Config
    from fengshen_tpu.models.gpt2 import convert

    hf_cfg = transformers.GPT2Config(
        vocab_size=128, n_positions=64, n_embd=32, n_layer=2, n_head=4,
        attn_implementation="eager")
    torch.manual_seed(0)
    tm = transformers.GPT2LMHeadModel(hf_cfg).eval()
    cfg = GPT2Config(vocab_size=128, n_positions=64, n_embd=32,
                     n_layer=2, n_head=4, dtype="float32",
                     scan_layers=True)
    return convert, tm.state_dict(), cfg, {}


def _bert():
    import transformers

    from fengshen_tpu.models.bert import BertConfig
    from fengshen_tpu.models.bert import convert

    hf_cfg = transformers.BertConfig(
        vocab_size=120, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, attn_implementation="eager")
    torch.manual_seed(0)
    tm = transformers.BertForMaskedLM(hf_cfg).eval()
    cfg = BertConfig(vocab_size=120, hidden_size=32, num_hidden_layers=2,
                     num_attention_heads=4, intermediate_size=64,
                     max_position_embeddings=64, dtype="float32")
    return convert, tm.state_dict(), cfg, {}


def _clip_vision():
    import transformers

    from fengshen_tpu.models.clip import CLIPVisionConfig
    from fengshen_tpu.models.clip import convert as clip_convert

    hf_cfg = transformers.CLIPVisionConfig(
        hidden_size=32, intermediate_size=64, num_hidden_layers=2,
        num_attention_heads=4, image_size=32, patch_size=8,
        attn_implementation="eager")
    torch.manual_seed(0)
    tm = transformers.CLIPVisionModel(hf_cfg).eval()
    cfg = CLIPVisionConfig(hidden_size=32, intermediate_size=64,
                           num_hidden_layers=2, num_attention_heads=4,
                           image_size=32, patch_size=8, dtype="float32")

    class _Shim:
        torch_to_params = staticmethod(clip_convert.vision_to_params)
        params_to_torch_state = staticmethod(
            lambda p, c, t, **kw: clip_convert.vision_params_to_torch_state(
                p, c, t))

    return _Shim, tm.state_dict(), cfg, {}


def _hubert():
    import transformers

    from fengshen_tpu.models.hubert import HubertConfig
    from fengshen_tpu.models.hubert import convert

    hf_cfg = transformers.HubertConfig(
        hidden_size=32, num_hidden_layers=2, num_attention_heads=4,
        intermediate_size=64, conv_dim=(16, 16), conv_kernel=(10, 3),
        conv_stride=(5, 2), num_feat_extract_layers=2,
        num_conv_pos_embeddings=7, num_conv_pos_embedding_groups=4,
        feat_extract_norm="group", do_stable_layer_norm=False,
        conv_bias=False, attn_implementation="eager")
    torch.manual_seed(0)
    tm = transformers.HubertModel(hf_cfg).eval()
    cfg = HubertConfig(conv_layers=((16, 10, 5), (16, 3, 2)),
                       hidden_size=32, num_hidden_layers=2,
                       num_attention_heads=4, intermediate_size=64,
                       pos_conv_kernel=7, pos_conv_groups=4)
    return convert, tm.state_dict(), cfg, {}


FAMILIES = {"bart": _bart, "pegasus": _pegasus, "deberta_v2": _deberta,
            "roformer": _roformer, "longformer": _longformer,
            "albert": _albert, "deltalm": _deltalm, "gpt2": _gpt2,
            "bert": _bert, "clip_vision": _clip_vision}


def _tiny_bert_cfg():
    import transformers
    return transformers.BertConfig(
        vocab_size=64, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=32, type_vocab_size=2,
        attn_implementation="eager")


def _our_bert_cfg():
    from fengshen_tpu.models.megatron_bert import MegatronBertConfig
    return MegatronBertConfig(
        vocab_size=64, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=32, type_vocab_size=2, dtype="float32")


def _unimc():
    import transformers

    from fengshen_tpu.models.unimc import convert

    torch.manual_seed(0)
    tm = transformers.BertForMaskedLM(_tiny_bert_cfg()).eval()
    # Lightning format: model. prefix + non-tensor metadata on the side
    sd = {f"model.bert.{k}": v for k, v in tm.state_dict().items()}
    sd["epoch"] = 3  # type: ignore[assignment]
    return convert, sd, _our_bert_cfg(), {}


def _ubert():
    import transformers

    from fengshen_tpu.models.ubert import convert

    torch.manual_seed(1)
    tower = transformers.BertModel(_tiny_bert_cfg()).eval()
    d = 8
    sd = {f"model.bert.{k}": v for k, v in tower.state_dict().items()}
    rng = np.random.RandomState(0)
    for name in ("query_layer.0", "key_layer.0"):
        sd[f"model.{name}.weight"] = torch.tensor(
            rng.randn(d, 32).astype(np.float32))
        sd[f"model.{name}.bias"] = torch.tensor(
            rng.randn(d).astype(np.float32))
    sd["model.biaffine_query_key_cls.U"] = torch.tensor(
        rng.randn(d + 1, 1, d + 1).astype(np.float32))
    return convert, sd, _our_bert_cfg(), {}


def _uniex():
    import transformers

    from fengshen_tpu.models.uniex import convert

    torch.manual_seed(2)
    tower = transformers.BertModel(_tiny_bert_cfg()).eval()
    d = 8
    sd = {f"model.bert.{k}": v for k, v in tower.state_dict().items()}
    rng = np.random.RandomState(1)
    for n in ("mlp_start", "mlp_end", "mlp_cls"):
        sd[f"model.{n}.mlp.0.weight"] = torch.tensor(
            rng.randn(d, 32).astype(np.float32))
        sd[f"model.{n}.mlp.0.bias"] = torch.tensor(
            rng.randn(d).astype(np.float32))
    sd["model.triaffine.weight"] = torch.tensor(
        rng.randn(d, d, d).astype(np.float32))
    return convert, sd, _our_bert_cfg(), {}


def _tcbert():
    import transformers

    from fengshen_tpu.models.tcbert import convert

    torch.manual_seed(3)
    tm = transformers.BertForMaskedLM(_tiny_bert_cfg()).eval()
    sd = {f"model.bert.{k}": v for k, v in tm.state_dict().items()}
    rng = np.random.RandomState(2)
    sd["model.linear_classifier.weight"] = torch.tensor(
        rng.randn(5, 32).astype(np.float32))
    sd["model.linear_classifier.bias"] = torch.tensor(
        rng.randn(5).astype(np.float32))
    return convert, sd, _our_bert_cfg(), {}


LIGHTNING_FAMILIES = {"unimc": _unimc, "ubert": _ubert,
                      "uniex": _uniex, "tcbert": _tcbert}


@pytest.mark.parametrize("family", sorted(LIGHTNING_FAMILIES))
def test_lightning_family_export_echo(family):
    """The task-head families import from Lightning-format checkpoints
    (model. prefix, metadata keys); export(import(ckpt)) must echo every
    tensor exactly — positions the import pads/synthesizes keep template
    values — and perturbed exports must at least invert cleanly."""
    convert, state, cfg, kw = LIGHTNING_FAMILIES[family]()
    tensor_keys = {k for k, v in state.items() if hasattr(v, "detach")}
    params = convert.torch_to_params(state, cfg, **kw)
    out = convert.params_to_torch_state(params, cfg, state, **kw)
    assert set(out) == tensor_keys
    for k in tensor_keys:
        np.testing.assert_array_equal(
            out[k], state[k].detach().numpy(),
            err_msg=f"{family}: {k}")
    # perturbed export still inverts without error (mixed-tag leaves
    # from padded heads must be handled, not crash)
    leaves, treedef = jax.tree_util.tree_flatten(params)
    bumped = jax.tree_util.tree_unflatten(
        treedef, [np.asarray(x) + 1e-3 for x in leaves])
    out2 = convert.params_to_torch_state(bumped, cfg, state, **kw)
    assert set(out2) == tensor_keys


def test_hubert_export_weight_norm_round_trip():
    """HuBERT's pos-conv weight-norm is collapsed on import, so the
    export re-decomposes it: the (g, v) pair differs from the source
    checkpoint but represents the SAME effective weight — verified by
    re-import identity and by torch reproducing the hidden states from
    the exported dict."""
    import transformers

    convert, state, cfg, kw = _hubert()
    params = convert.torch_to_params(state, cfg)
    out = convert.params_to_torch_state(params, cfg, state, **kw)
    assert set(out) == set(state)
    back = convert.torch_to_params(out, cfg)
    for (pa, a), (pb, b) in zip(
            jax.tree_util.tree_flatten_with_path(params)[0],
            jax.tree_util.tree_flatten_with_path(back)[0]):
        assert pa == pb
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-6, err_msg=str(pa))
    # torch loads the export and produces identical features
    hf_cfg = transformers.HubertConfig(
        hidden_size=32, num_hidden_layers=2, num_attention_heads=4,
        intermediate_size=64, conv_dim=(16, 16), conv_kernel=(10, 3),
        conv_stride=(5, 2), num_feat_extract_layers=2,
        num_conv_pos_embeddings=7, num_conv_pos_embedding_groups=4,
        feat_extract_norm="group", do_stable_layer_norm=False,
        conv_bias=False, attn_implementation="eager")
    torch.manual_seed(1)
    tm0 = transformers.HubertModel(hf_cfg).eval()
    missing, _ = tm0.load_state_dict(
        {k: torch.tensor(np.ascontiguousarray(v))
         for k, v in out.items()}, strict=False)
    assert not missing, missing
    torch.manual_seed(2)
    tm1 = transformers.HubertModel(hf_cfg).eval()
    tm1.load_state_dict(state)
    wav = torch.tensor(np.random.RandomState(3).randn(1, 400),
                       dtype=torch.float32)
    with torch.no_grad():
        np.testing.assert_allclose(
            tm0(wav).last_hidden_state.numpy(),
            tm1(wav).last_hidden_state.numpy(), atol=1e-6)


def test_export_follows_tied_duplicates():
    """Keys the importer never reads but that are TIED to read tensors
    (GPT2's lm_head.weight ↔ wte) must track the finetuned values — a
    stale copy would be load_state_dict'ed into the shared storage last
    and silently revert the finetune."""
    convert, state, cfg, kw = _gpt2()
    assert "lm_head.weight" in state  # torch materializes the tied key
    params = convert.torch_to_params(state, cfg, **kw)
    leaves, treedef = jax.tree_util.tree_flatten(params)
    bumped = jax.tree_util.tree_unflatten(
        treedef, [np.asarray(x) + 1e-3 for x in leaves])
    out = convert.params_to_torch_state(bumped, cfg, state, **kw)
    np.testing.assert_array_equal(out["lm_head.weight"],
                                  out["transformer.wte.weight"])
    assert not np.array_equal(
        out["lm_head.weight"],
        state["lm_head.weight"].detach().numpy())  # not the stale copy


def test_export_preserves_template_dtype():
    """An fp16/bf16 source checkpoint exports back in its own dtype."""
    convert, state, cfg, kw = _bart()
    state16 = {k: v.half() for k, v in state.items()}
    params = convert.torch_to_params(state16, cfg, **kw)
    out = convert.params_to_torch_state(params, cfg, state16, **kw)
    assert all(v.dtype == np.float16 for v in out.values()), \
        {k: v.dtype for k, v in out.items() if v.dtype != np.float16}


@pytest.mark.parametrize("family", sorted(FAMILIES))
def test_export_round_trip(family):
    convert, state, cfg, kw = FAMILIES[family]()
    ref = {k: np.array(v.detach().numpy() if hasattr(v, "detach") else v)
           for k, v in state.items()}
    params = convert.torch_to_params(state, cfg, **kw)

    # 1. export of the untouched import reproduces the source state dict
    #    exactly — read keys round-trip, unread keys keep template values
    out = convert.params_to_torch_state(params, cfg, state, **kw)
    assert set(out) == set(ref)
    for k in ref:
        np.testing.assert_array_equal(
            out[k].astype(np.float32), ref[k].astype(np.float32),
            err_msg=f"{family}: {k}")

    # 2. a "finetuned" tree survives export → re-import bit-exactly
    leaves, treedef = jax.tree_util.tree_flatten(params)
    bumped = jax.tree_util.tree_unflatten(
        treedef, [np.asarray(x) + (i % 13) * 1e-3
                  for i, x in enumerate(leaves)])
    out2 = convert.params_to_torch_state(bumped, cfg, state, **kw)
    back = convert.torch_to_params(out2, cfg, **kw)
    for path_a, a in jax.tree_util.tree_flatten_with_path(bumped)[0]:
        b = dict(jax.tree_util.tree_flatten_with_path(back)[0])[path_a]
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=0, atol=1e-6,
            err_msg=f"{family}: {jax.tree_util.keystr(path_a)}")


@pytest.mark.parametrize("seed", [0, 1, 2, 3, 4])
def test_invert_import_property_random_importers(seed):
    """Property test for the cornerstone: for a RANDOM permutation-style
    importer (transposes / reshapes / stacks / slices / key renames over
    a random template), invert_import must reproduce the template
    exactly and round-trip arbitrary values bit-exactly."""
    from fengshen_tpu.utils.convert_common import invert_import

    rng = np.random.RandomState(seed)
    n_keys = rng.randint(3, 8)
    template = {}
    ops = []
    for i in range(n_keys):
        shape = tuple(rng.randint(1, 5, size=rng.randint(1, 4)))
        template[f"w{i}.weight"] = rng.randn(*shape).astype(np.float32)
        ops.append(rng.choice(["id", "T", "flat", "flip"]))
    def importer(sd):
        # stacking of same-shaped keys is exercised by the real scan
        # families (gpt2/llama round-trips); here: pure per-key permutes
        out = {}
        for i in range(n_keys):
            arr = np.asarray(sd[f"w{i}.weight"])
            op = ops[i]
            if op == "T":
                arr = arr.T
            elif op == "flat":
                arr = arr.reshape(-1)
            elif op == "flip":
                arr = arr[::-1]
            out[f"leaf_{i}"] = {"kernel": arr}
        return out

    params = importer(template)
    out = invert_import(importer, template, None, params)
    assert set(out) == set(template)
    for k in template:
        np.testing.assert_array_equal(out[k], template[k], err_msg=k)

    # arbitrary new values round-trip through export → import
    bumped = jax.tree_util.tree_map(
        lambda x: np.asarray(x) + rng.randn(*np.shape(x)).astype(
            np.float32), params)
    out2 = invert_import(importer, template, None, bumped)
    back = importer(out2)
    for (pa, a), (pb, b) in zip(
            jax.tree_util.tree_flatten_with_path(bumped)[0],
            jax.tree_util.tree_flatten_with_path(back)[0]):
        assert pa == pb
        np.testing.assert_allclose(a, b, atol=1e-5,
                                   err_msg=jax.tree_util.keystr(pa))


@pytest.mark.parametrize("op", ["sum2", "sum4", "diff", "scale"])
def test_invert_import_rejects_arithmetic_importer(op):
    """Importers that do arithmetic — 2- and 4-way sums (the latter
    yields INTEGRAL tag combinations), differences, scales — must raise
    loudly, never return a silently stale inverse."""
    from fengshen_tpu.utils.convert_common import invert_import

    template = {f"{c}.weight": np.ones((4, 4), np.float32)
                for c in "abcd"}

    def importer(sd):
        a, b, c, d = (np.asarray(sd[f"{k}.weight"]) for k in "abcd")
        # note a plain aligned `a - b` of tag grids is CONSTANT and
        # thus indistinguishable from a constant init (skipped); the
        # transposed diff below is the realistic non-degenerate case
        leaf = {"sum2": a + b, "sum4": a + b + c + d, "diff": a - b.T,
                "scale": 2.0 * a}[op]
        return {"leaf": {"kernel": leaf}}

    params = importer(template)
    with pytest.raises(ValueError,
                       match="arithmetic|hand-written inverse"):
        invert_import(importer, template, None, params)


def test_invert_import_allows_constant_synthesized_leaves():
    """Constant-init synthesized leaves (zeros, ones, 0.5-fills) are
    skipped, not mistaken for arithmetic."""
    from fengshen_tpu.utils.convert_common import invert_import

    template = {"a.weight": np.random.RandomState(0).randn(
        4, 4).astype(np.float32)}

    def importer(sd):
        return {"real": {"kernel": np.asarray(sd["a.weight"]).T},
                "gate": {"bias": np.full((8,), 0.5, np.float32)},
                "zeros": {"kernel": np.zeros((3, 3), np.float32)}}

    params = importer(template)
    out = invert_import(importer, template, None, params)
    np.testing.assert_array_equal(out["a.weight"], template["a.weight"])
