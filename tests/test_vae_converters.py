"""VAE-family importer tests (VERDICT r2 item 3): davae, ppvae, gavae,
deepvae. Oracles: HF towers from transformers where the reference uses
them, numpy/torch restatements of the reference head math elsewhere.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

from test_transfo_xl_convert import (_layer as xl_layer,  # noqa: E402
                                     _ln, _pos_emb, _sd as xl_sd,
                                     H, NH, NL, V)


# --------------------------------------------------------------- davae --

def test_davae_convert_forward_parity():
    """Reference DAVAE (DAVAEModel.py:35-140): bert pooled → bias-free
    linear posterior; GLM relative decoder with latent injected after the
    embedding and after every layer; tied logits."""
    import jax.numpy as jnp
    from transformers import BertConfig as HFBertConfig
    from transformers import BertModel as HFBert

    from fengshen_tpu.models.bert.modeling_bert import BertConfig
    from fengshen_tpu.models.davae.convert import torch_to_params
    from fengshen_tpu.models.davae.modeling_davae import (DAVAEConfig,
                                                          DAVAEModel)
    from fengshen_tpu.models.gpt2 import GPT2Config

    LAT = 4
    torch.manual_seed(0)
    enc = HFBert(HFBertConfig(
        vocab_size=64, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=32, type_vocab_size=2)).eval()
    linear = torch.nn.Linear(32, 2 * LAT, bias=False)

    dec_sd = xl_sd()  # reference-named GLM decoder weights
    rng = np.random.RandomState(9)
    linear_emb = rng.randn(H, LAT).astype(np.float32) * 0.1

    sd = {f"vae_model.encoder.{k}": v for k, v in enc.state_dict().items()}
    sd["vae_model.encoder.linear.weight"] = linear.weight
    for k, v in dec_sd.items():
        sd[f"vae_model.decoder.{k}"] = v
    sd["vae_model.decoder.transformer.linear_emb.weight"] = linear_emb

    cfg = DAVAEConfig(
        latent_size=LAT, relative_decoder=True,
        encoder=BertConfig(
            vocab_size=64, hidden_size=32, num_hidden_layers=2,
            num_attention_heads=4, intermediate_size=64,
            max_position_embeddings=32, type_vocab_size=2,
            dtype="float32"),
        decoder=GPT2Config(vocab_size=V, n_embd=H, n_layer=NL, n_head=NH,
                           n_positions=32, dtype="float32"))
    params = torch_to_params(sd, cfg)
    model = DAVAEModel(cfg)

    ids = np.random.RandomState(1).randint(0, 64, (2, 8))
    dec_ids = np.random.RandomState(2).randint(0, V, (2, 6))
    logits, mean, logvar, latent = model.apply(
        {"params": params}, jnp.asarray(ids),
        decoder_input_ids=jnp.asarray(dec_ids))

    with torch.no_grad():
        pooled = enc(torch.tensor(ids, dtype=torch.long)).pooler_output
        stats = linear(pooled).numpy()
    ref_mean, ref_logvar = stats[:, :LAT], stats[:, LAT:]
    np.testing.assert_allclose(np.asarray(mean), ref_mean, atol=2e-4)
    np.testing.assert_allclose(np.asarray(logvar), ref_logvar, atol=2e-4)

    # decoder oracle: xl layers + latent injection (GPT2ModelForLatent
    # :500-575), tied logits
    lat_emb = (ref_mean @ linear_emb.T)[:, None, :]
    hidden = dec_sd["word_embeddings.weight"][dec_ids] + lat_emb
    qlen = dec_ids.shape[1]
    ltor = np.tril(np.ones((qlen, qlen), np.float32))[None, None]
    pos = _pos_emb(qlen)
    for i in range(NL):
        hidden = xl_layer(dec_sd, i, hidden, ltor, pos) + lat_emb
    hidden = _ln(hidden, dec_sd["transformer.final_layernorm.weight"],
                 dec_sd["transformer.final_layernorm.bias"])
    ref_logits = hidden @ dec_sd["word_embeddings.weight"].T
    np.testing.assert_allclose(np.asarray(logits), ref_logits, atol=5e-4)


def test_davae_critic_convert():
    from fengshen_tpu.models.davae.convert import critic_to_params
    from fengshen_tpu.models.davae.modeling_davae import LatentCritic
    import jax.numpy as jnp

    LAT = 4
    rng = np.random.RandomState(3)
    sd = {
        "vae_model.Disc.0.weight": rng.randn(4 * LAT, LAT).astype(
            np.float32),
        "vae_model.Disc.0.bias": rng.randn(4 * LAT).astype(np.float32),
        "vae_model.Disc.2.weight": rng.randn(1, 4 * LAT).astype(
            np.float32),
        "vae_model.Disc.2.bias": rng.randn(1).astype(np.float32),
    }
    params = critic_to_params(sd)
    z = rng.randn(3, LAT).astype(np.float32)
    out = LatentCritic(hidden=4 * LAT).apply({"params": params},
                                             jnp.asarray(z))
    h = np.maximum(z @ sd["vae_model.Disc.0.weight"].T +
                   sd["vae_model.Disc.0.bias"], 0)
    ref = h @ sd["vae_model.Disc.2.weight"].T + sd["vae_model.Disc.2.bias"]
    np.testing.assert_allclose(np.asarray(out), ref[:, 0], atol=1e-5)


# --------------------------------------------------------------- ppvae --

def test_ppvae_convert_forward_parity():
    """PluginVAE bottleneck (pluginVAE.py:13-78): leaky-relu enc/dec
    MLPs; deterministic path uses the mean."""
    import jax.numpy as jnp

    from fengshen_tpu.models.ppvae.convert import torch_to_params
    from fengshen_tpu.models.ppvae.modeling_ppvae import PluginVAE

    LD, BD = 16, 4
    rng = np.random.RandomState(5)

    def lin(i, o):
        return (rng.randn(o, i).astype(np.float32) * 0.3,
                rng.randn(o).astype(np.float32) * 0.1)

    names = {"encoder.fc1": lin(LD, LD // 2),
             "encoder.fc2": lin(LD // 2, LD // 4),
             "encoder.mean": lin(LD // 4, BD),
             "encoder.log_var": lin(LD // 4, BD),
             "decoder.fc1": lin(BD, LD // 4),
             "decoder.fc2": lin(LD // 4, LD // 2),
             "decoder.fc3": lin(LD // 2, LD)}
    sd = {}
    for n, (w, b) in names.items():
        sd[f"pluginvae.{n}.weight"] = w
        sd[f"pluginvae.{n}.bias"] = b

    params = torch_to_params(sd)
    z = rng.randn(3, LD).astype(np.float32)
    out, kl = PluginVAE(latent_dim=LD, bottle_dim=BD).apply(
        {"params": params}, jnp.asarray(z))

    def leaky(x):
        return np.where(x > 0, x, 0.01 * x)

    def ln_np(x, name):
        w, b = names[name]
        return x @ w.T + b

    h = leaky(ln_np(z, "encoder.fc1"))
    h = leaky(ln_np(h, "encoder.fc2"))
    mean = ln_np(h, "encoder.mean")
    d = leaky(ln_np(mean, "decoder.fc1"))
    d = leaky(ln_np(d, "decoder.fc2"))
    ref = ln_np(d, "decoder.fc3")
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-5)


# --------------------------------------------------------------- gavae --

def test_gavae_net_converts():
    """Gen_Net / CLS_Net (gans_model.py): relu chains with the reference
    dims; discriminator gains a zero fake-class row."""
    import jax.numpy as jnp

    from fengshen_tpu.models.gavae.convert import (cls_to_params,
                                                   gen_to_params)
    from fengshen_tpu.models.gavae.modeling_gavae import (
        LatentDiscriminator, LatentGenerator)

    LAT, IN = 6, 10
    rng = np.random.RandomState(6)

    def lin(i, o):
        return (rng.randn(o, i).astype(np.float32) * 0.2,
                rng.randn(o).astype(np.float32) * 0.1)

    gen_layers = {"x2_input": lin(IN, 60), "fc1": lin(60, 128),
                  "fc2": lin(128, 256), "fc3": lin(256, 128),
                  "out": lin(128, LAT)}
    sd = {}
    for n, (w, b) in gen_layers.items():
        sd[f"{n}.weight"] = w
        sd[f"{n}.bias"] = b
    params = gen_to_params(sd)
    x = rng.randn(3, IN).astype(np.float32)
    out = LatentGenerator(LAT).apply({"params": params}, jnp.asarray(x))

    def fwd(x):
        h = x @ gen_layers["x2_input"][0].T + gen_layers["x2_input"][1]
        for n in ("fc1", "fc2", "fc3"):
            h = np.maximum(h @ gen_layers[n][0].T + gen_layers[n][1], 0)
        return h @ gen_layers["out"][0].T + gen_layers["out"][1]

    np.testing.assert_allclose(np.asarray(out), fwd(x), atol=1e-5)

    cls_layers = {"fc1": lin(LAT, 256), "fc2": lin(256, 64),
                  "out": lin(64, 2)}
    sd = {}
    for n, (w, b) in cls_layers.items():
        sd[f"{n}.weight"] = w
        sd[f"{n}.bias"] = b
    params = cls_to_params(sd)
    z = rng.randn(3, LAT).astype(np.float32)
    logits = LatentDiscriminator(cls_num=2).apply({"params": params},
                                                  jnp.asarray(z))
    assert logits.shape == (3, 3)
    h = np.maximum(z @ cls_layers["fc1"][0].T + cls_layers["fc1"][1], 0)
    h = np.maximum(h @ cls_layers["fc2"][0].T + cls_layers["fc2"][1], 0)
    ref = h @ cls_layers["out"][0].T + cls_layers["out"][1]
    np.testing.assert_allclose(np.asarray(logits[:, :2]), ref, atol=1e-5)
    np.testing.assert_allclose(np.asarray(logits[:, 2]), 0.0, atol=1e-6)


# ------------------------------------------------------------- deepvae --

def test_della_convert_forward_parity():
    """Della end-to-end vs a torch oracle built from HF GPT2 towers plus
    the reference latent flow (deep_vae.py:111-139, latent_connector.py:
    155-180): separate enc/dec towers, per-layer pooling on HF
    hidden_states[1:], bias-free posterior/prior nets, tanh latent
    combiner, injection before each decoder block, untied lm_head."""
    import jax.numpy as jnp
    from transformers import GPT2Config as HFGPT2Config
    from transformers import GPT2Model as HFGPT2

    from fengshen_tpu.models.deepvae.convert import torch_to_params
    from fengshen_tpu.models.deepvae.modeling_deepvae import (DellaConfig,
                                                              DellaModel)
    from fengshen_tpu.models.gpt2 import GPT2Config

    LAT, HID, L = 4, 24, 2
    hf_cfg = HFGPT2Config(vocab_size=48, n_positions=32, n_embd=HID,
                          n_layer=L, n_head=4)
    torch.manual_seed(8)
    enc = HFGPT2(hf_cfg).eval()
    dec = HFGPT2(hf_cfg).eval()
    lm_head = torch.nn.Linear(HID, 48, bias=False)
    linear_embs = [torch.nn.Linear(LAT, HID, bias=False)
                   for _ in range(L)]
    post_nets = [torch.nn.Linear(HID + LAT, 2 * LAT, bias=False)
                 for _ in range(L)]
    prior_nets = [torch.nn.Linear(LAT, 2 * LAT, bias=False)
                  for _ in range(L)]
    w_hh = [torch.nn.Linear(LAT, LAT, bias=False) for _ in range(L - 1)]
    w_ih = [torch.nn.Linear(LAT, LAT, bias=False) for _ in range(L - 1)]
    pool_w = [torch.randn(HID) * 0.02 for _ in range(L)]

    sd = {}
    for k, v in enc.state_dict().items():
        sd[f"encoder.transformer.{k}"] = v
    for k, v in dec.state_dict().items():
        sd[f"decoder.transformer.{k}"] = v
    sd["decoder.lm_head.weight"] = lm_head.weight
    for i in range(L):
        sd[f"decoder.transformer.linear_emb_layers.{i}.weight"] = \
            linear_embs[i].weight
        sd[f"posterior_nets.{i}.weight"] = post_nets[i].weight
        sd[f"prior_nets.{i}.weight"] = prior_nets[i].weight
        sd[f"pooling.{i}.attention_weights"] = pool_w[i]
    for i in range(L - 1):
        sd[f"latent_nets.{i}.W_hh.weight"] = w_hh[i].weight
        sd[f"latent_nets.{i}.W_ih.weight"] = w_ih[i].weight

    cfg = DellaConfig(latent_dim=LAT,
                      gpt2=GPT2Config(vocab_size=48, n_positions=32,
                                      n_embd=HID, n_layer=L, n_head=4,
                                      dtype="float32"))
    params = torch_to_params(sd, cfg)
    model = DellaModel(cfg)
    ids = np.random.RandomState(10).randint(0, 48, (2, 7))
    logits, posts, priors = model.apply({"params": params},
                                        jnp.asarray(ids))

    with torch.no_grad():
        tids = torch.tensor(ids, dtype=torch.long)
        enc_out = enc(tids, output_hidden_states=True)
        layer_states = enc_out.hidden_states[1:]  # block outs, last ln_f'd
        z = torch.zeros(2, LAT)
        zs = []
        ref_posts = []
        for i in range(L):
            scores = torch.softmax(
                torch.tanh(layer_states[i] @ pool_w[i]), -1)
            rep = (layer_states[i] * scores[..., None]).sum(1)
            stats = post_nets[i](torch.cat([rep, z], -1))
            mean = stats[:, :LAT]
            zs.append(mean)
            ref_posts.append(stats)
            if i < L - 1:
                z = torch.tanh(w_hh[i](z) + w_ih[i](mean))
        # decoder with injection BEFORE each block
        pos = torch.arange(ids.shape[1])[None]
        hs = dec.wte(tids) + dec.wpe(pos)
        for i in range(L):
            hs = hs + linear_embs[i](zs[i])[:, None, :]
            hs = dec.h[i](hs)[0]
        hs = dec.ln_f(hs)
        ref_logits = lm_head(hs).numpy()

    for i in range(L):
        got = np.concatenate([np.asarray(posts[i][0]),
                              np.asarray(posts[i][1])], -1)
        np.testing.assert_allclose(got, ref_posts[i].numpy(), atol=3e-4)
    np.testing.assert_allclose(np.asarray(logits), ref_logits, atol=2e-3)


def test_ppvae_export_echo():
    """fs→reference export for the config-free PluginVAE importer."""
    from fengshen_tpu.models.ppvae.convert import (params_to_torch_state,
                                                   torch_to_params)

    LD, BD = 16, 4
    rng = np.random.RandomState(5)

    def lin(i, o):
        return (rng.randn(o, i).astype(np.float32) * 0.3,
                rng.randn(o).astype(np.float32) * 0.1)

    sd = {}
    for n, (i, o) in (("encoder.fc1", (LD, LD // 2)),
                      ("encoder.fc2", (LD // 2, LD // 4)),
                      ("encoder.mean", (LD // 4, BD)),
                      ("encoder.log_var", (LD // 4, BD)),
                      ("decoder.fc1", (BD, LD // 4)),
                      ("decoder.fc2", (LD // 4, LD // 2)),
                      ("decoder.fc3", (LD // 2, LD))):
        w, b = lin(i, o)
        sd[f"pluginvae.{n}.weight"] = w
        sd[f"pluginvae.{n}.bias"] = b

    params = torch_to_params(sd)
    out = params_to_torch_state(params, None, sd)
    assert set(out) == set(sd)
    for k in sd:
        np.testing.assert_array_equal(out[k], sd[k], err_msg=k)
