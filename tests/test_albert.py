"""ALBERT golden-value parity vs HF torch."""

import jax.numpy as jnp
import numpy as np
import pytest

from fengshen_tpu.models.albert import AlbertConfig, AlbertModel
from fengshen_tpu.models.albert.convert import torch_to_params


def test_albert_forward_parity():
    torch = pytest.importorskip("torch")
    import transformers
    hf_cfg = transformers.AlbertConfig(
        vocab_size=128, embedding_size=16, hidden_size=32,
        num_hidden_layers=3, num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, attn_implementation="eager")
    torch.manual_seed(0)
    tm = transformers.AlbertModel(hf_cfg).eval()
    cfg = AlbertConfig(vocab_size=128, embedding_size=16, hidden_size=32,
                       num_hidden_layers=3, num_attention_heads=4,
                       intermediate_size=64, max_position_embeddings=64,
                       dtype="float32")
    sd = {f"albert.{k}" if not k.startswith("albert.") else k: v
          for k, v in tm.state_dict().items()}
    params = torch_to_params(sd, cfg)
    ids = np.array([[3, 17, 9, 42, 7, 99, 1, 5]], dtype=np.int32)
    mask = np.array([[1, 1, 1, 1, 1, 1, 1, 0]], dtype=np.int32)
    hidden, pooled = AlbertModel(cfg).apply(
        {"params": params}, jnp.asarray(ids),
        attention_mask=jnp.asarray(mask))
    with torch.no_grad():
        out = tm(torch.tensor(ids, dtype=torch.long),
                 attention_mask=torch.tensor(mask, dtype=torch.long))
    np.testing.assert_allclose(np.asarray(hidden),
                               out.last_hidden_state.numpy(), atol=2e-3)
    np.testing.assert_allclose(np.asarray(pooled),
                               out.pooler_output.numpy(), atol=2e-3)
