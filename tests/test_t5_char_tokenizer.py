"""Randeng-T5-Char tokenizer + char-tokenizer recipes (VERDICT r3
missing #3 / next-round item 4)."""

import json
import os

import numpy as np
import pytest

CHARS = list("今天天气很好糟糕新闻标题体育财经科技故事内容问题答案是否")


def _char_model_dir(tmp_path, with_markers=True, config_extra=None):
    specials = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"]
    if with_markers:
        specials += ["[BOS]", "[EOS]"]
    vocab = specials + sorted(set(CHARS))
    model_dir = tmp_path / "model"
    model_dir.mkdir(exist_ok=True)
    (model_dir / "vocab.txt").write_text("\n".join(vocab))
    cfg = {"model_type": "t5", "tokenizer_class": "megatron_t5",
           "vocab_size": len(vocab) + 120, "d_model": 32, "d_kv": 8,
           "d_ff": 64, "num_layers": 2, "num_heads": 4,
           "dtype": "float32"}
    cfg.update(config_extra or {})
    with open(model_dir / "config.json", "w") as f:
        json.dump(cfg, f)
    return model_dir


def test_round_trip_with_extra_ids(tmp_path):
    from fengshen_tpu.models.t5 import T5Tokenizer

    tok = T5Tokenizer.from_pretrained(str(_char_model_dir(tmp_path)))
    # char-level: each Chinese char is one token
    ids = tok.encode("今天天气", add_special_tokens=False)
    assert len(ids) == 4
    assert tok.decode(ids, skip_special_tokens=True).replace(" ", "") == \
        "今天天气"
    # 118 sentinels, round-trippable as single tokens
    assert len(tok.sentinel_token_ids) == 118
    s17 = tok.convert_tokens_to_ids("<extra_id_17>")
    assert s17 == tok.sentinel_token_ids[17]
    assert tok.convert_ids_to_tokens(s17) == "<extra_id_17>"
    # [BOS]/[EOS] bound as bos/eos
    assert tok.eos_token == "[EOS]" and tok.bos_token == "[BOS]"
    assert tok.eos_token_id == tok.convert_tokens_to_ids("[EOS]")


def test_span_corruption_uses_wrapper_sentinels(tmp_path):
    from fengshen_tpu.data.t5_dataloader.t5_datasets import (
        T5SpanCorruptionCollator)
    from fengshen_tpu.models.t5 import T5Tokenizer

    tok = T5Tokenizer.from_pretrained(str(_char_model_dir(tmp_path)))
    collator = T5SpanCorruptionCollator(tok, max_seq_length=32,
                                        noise_density=0.3)
    batch = collator([{"text": "".join(np.random.RandomState(0)
                                       .choice(CHARS, 24))}])
    sent = set(tok.sentinel_token_ids)
    used = [t for t in batch["input_ids"][0].tolist() if t in sent]
    assert used, "no sentinel tokens appeared in the corrupted input"
    # first span must use <extra_id_0>, second <extra_id_1>, ... (the
    # wrapper's ASCENDING ids, not len(vocab)-1-i)
    assert used[0] == tok.sentinel_token_ids[0]
    assert used == tok.sentinel_token_ids[: len(used)]


def test_auto_tokenizer_resolves_char_t5(tmp_path):
    from fengshen_tpu.models.auto import AutoTokenizer

    tok = AutoTokenizer.from_pretrained(str(_char_model_dir(tmp_path)))
    assert hasattr(tok, "sentinel_token_ids")
    # plain dirs still fall through to HF
    from transformers import BertTokenizer
    plain = tmp_path / "plain"
    plain.mkdir()
    (plain / "vocab.txt").write_text("\n".join(
        ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"] +
        sorted(set(CHARS))))
    BertTokenizer(str(plain / "vocab.txt")).save_pretrained(str(plain))
    hf = AutoTokenizer.from_pretrained(str(plain))
    assert not hasattr(hf, "sentinel_token_ids")


def test_process_data_and_convert_ckpt(tmp_path):
    import torch

    from fengshen_tpu.examples.pretrain_t5 import (convert_ckpt_to_bin,
                                                   process_data)

    model_dir = _char_model_dir(tmp_path)
    corpus = tmp_path / "corpus.jsonl"
    rng = np.random.RandomState(0)
    with open(corpus, "w") as f:
        for _ in range(10):
            f.write(json.dumps(
                {"text": "".join(rng.choice(CHARS, 20))},
                ensure_ascii=False) + "\n")
    process_data.main([
        "--tokenizer_type", "bert_tokenizer",
        "--train_data_path", str(corpus),
        "--train_split_size", "0.8",
        "--max_seq_length", "32",
        "--saved_data_shards", "2",
        "--saved_train_data_path", str(tmp_path / "train_shards"),
        "--saved_test_data_path", str(tmp_path / "test_shards"),
        "--pretrained_model_path", str(model_dir)])
    shards = sorted(os.listdir(tmp_path / "train_shards"))
    assert len(shards) == 2
    arr = np.load(str(tmp_path / "train_shards" / shards[0]),
                  allow_pickle=True)
    assert all(a.dtype == np.int32 for a in arr)
    total = sum(len(np.load(str(tmp_path / "train_shards" / s),
                            allow_pickle=True)) for s in shards)
    assert total == 8  # 0.8 split of 10

    # convert_ckpt_to_bin strips the DeepSpeed module.model. prefix
    ckpt = {"module": {"module.model.shared.weight": torch.ones(3),
                       "other.weight": torch.zeros(2)}}
    src = tmp_path / "mp_rank_00_model_states.pt"
    torch.save(ckpt, str(src))
    out = tmp_path / "pytorch_model.bin"
    convert_ckpt_to_bin.main(["--ckpt_path", str(src),
                              "--bin_path", str(out),
                              "--rm_prefix", "module.model."])
    state = torch.load(str(out), weights_only=True)
    assert set(state) == {"shared.weight", "other.weight"}


@pytest.mark.slow
def test_finetune_unimc_t5_char_e2e(tmp_path, mesh8, monkeypatch):
    """The char-57M launcher recipe end-to-end on a synthetic vocab:
    UniMC rows → fit 2 steps → choice-restricted val acc logged."""
    monkeypatch.chdir(tmp_path)
    model_dir = _char_model_dir(tmp_path)
    rng = np.random.RandomState(0)
    data_dir = tmp_path / "unimc"
    data_dir.mkdir()
    for name in ("train.json", "dev.json"):
        with open(data_dir / name, "w") as f:
            for i in range(8):
                f.write(json.dumps(
                    {"texta": "".join(rng.choice(CHARS, 10)),
                     "textb": "",
                     "question": "是否？", "choice": ["是", "否"],
                     "answer": ["是", "否"][i % 2], "label": i % 2,
                     "id": i}, ensure_ascii=False) + "\n")

    from fengshen_tpu.examples.pretrain_t5 import finetune_t5
    finetune_t5.main([
        "--pretrained_model_path", str(model_dir),
        "--tokenizer_type", "bert_tokenizer",
        "--train_data_path", str(data_dir / "train.json"),
        "--valid_data_path", str(data_dir / "dev.json"),
        "--train_batchsize", "4", "--val_batchsize", "4",
        "--max_seq_length", "32",
        "--max_steps", "2", "--max_epochs", "1",
        "--default_root_dir", str(tmp_path / "runs"),
        "--save_ckpt_path", str(tmp_path / "ckpt"),
        "--load_ckpt_path", str(tmp_path / "ckpt"),
        "--precision", "fp32",
    ])
    log = (tmp_path / "runs" / "metrics.jsonl").read_text()
    assert "cond_acc" in log
