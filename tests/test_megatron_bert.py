"""MegatronBert (Erlangshen) golden-value parity vs HF torch."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fengshen_tpu.models.megatron_bert import (MegatronBertConfig,
                                               MegatronBertForPreTraining)
from fengshen_tpu.models.megatron_bert.convert import torch_to_params


@pytest.fixture(scope="module")
def bert_pair():
    torch = pytest.importorskip("torch")
    import transformers

    hf_cfg = transformers.MegatronBertConfig(
        vocab_size=128, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, attn_implementation="eager")
    torch.manual_seed(0)
    tm = transformers.MegatronBertForPreTraining(hf_cfg).eval()
    cfg = MegatronBertConfig(vocab_size=128, hidden_size=32,
                             num_hidden_layers=2, num_attention_heads=4,
                             intermediate_size=64,
                             max_position_embeddings=64, dtype="float32")
    params = torch_to_params(tm.state_dict(), cfg)
    return params, tm, cfg


def test_pretraining_forward_parity(bert_pair):
    import torch
    params, tm, cfg = bert_pair
    ids = np.array([[2, 17, 9, 42, 7, 99, 1, 5]], dtype=np.int32)
    mask = np.array([[1, 1, 1, 1, 1, 1, 0, 0]], dtype=np.int32)
    types = np.array([[0, 0, 0, 0, 1, 1, 1, 1]], dtype=np.int32)
    mlm, sop = MegatronBertForPreTraining(cfg).apply(
        {"params": params}, jnp.asarray(ids),
        attention_mask=jnp.asarray(mask), token_type_ids=jnp.asarray(types))
    with torch.no_grad():
        out = tm(torch.tensor(ids, dtype=torch.long),
                 attention_mask=torch.tensor(mask, dtype=torch.long),
                 token_type_ids=torch.tensor(types, dtype=torch.long))
    np.testing.assert_allclose(np.asarray(mlm),
                               out.prediction_logits.numpy(), atol=2e-3)
    np.testing.assert_allclose(np.asarray(sop),
                               out.seq_relationship_logits.numpy(),
                               atol=2e-3)


def test_bert_sharded_matches_replicated(bert_pair, mesh8):
    params, _, cfg = bert_pair
    model = MegatronBertForPreTraining(cfg)
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 127, (4, 16)),
                      jnp.int32)
    mlm_ref, sop_ref = model.apply({"params": params}, ids)
    from fengshen_tpu.parallel import make_shardings
    shardings = make_shardings(model.partition_rules(), params, mesh8)
    sharded = jax.device_put(params, shardings)
    mlm, sop = jax.jit(lambda p, i: model.apply({"params": p}, i))(
        sharded, ids)
    np.testing.assert_allclose(np.asarray(mlm), np.asarray(mlm_ref),
                               atol=2e-4)
    np.testing.assert_allclose(np.asarray(sop), np.asarray(sop_ref),
                               atol=2e-4)


def test_scan_layers_parity(bert_pair):
    import dataclasses
    params, tm, cfg = bert_pair
    scan_cfg = dataclasses.replace(cfg, scan_layers=True)
    scan_params = torch_to_params(tm.state_dict(), scan_cfg)
    ids = np.array([[2, 17, 9, 42]], dtype=np.int32)
    ref_mlm, ref_sop = MegatronBertForPreTraining(cfg).apply(
        {"params": params}, jnp.asarray(ids))
    mlm, sop = MegatronBertForPreTraining(scan_cfg).apply(
        {"params": scan_params}, jnp.asarray(ids))
    np.testing.assert_allclose(np.asarray(mlm), np.asarray(ref_mlm),
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(sop), np.asarray(ref_sop),
                               atol=1e-5)
