"""Smoke tests for the multimodal example workloads: hubert pretrain,
taiyi-clip pretrain, taiyi-SD finetune, dreambooth — tiny data, CPU mesh."""

import json
import wave



import numpy as np
import pytest

pytestmark = pytest.mark.slow  # full-fit/e2e lane: run with -m slow or no -m filter



# ---------------------------------------------------------------------------
# hubert
# ---------------------------------------------------------------------------

def _write_wav(path, n_samples, sr=16000, seed=0):
    rng = np.random.RandomState(seed)
    pcm = (rng.randn(n_samples) * 3000).astype(np.int16)
    with wave.open(str(path), "wb") as w:
        w.setnchannels(1)
        w.setsampwidth(2)
        w.setframerate(sr)
        w.writeframes(pcm.tobytes())


def _hubert_data(tmp_path, n_rows=4, n_samples=4000, label_rate=50.0):
    audio_dir = tmp_path / "audio"
    audio_dir.mkdir()
    with open(tmp_path / "train.tsv", "w") as mf:
        mf.write(str(audio_dir) + "\n")
        for i in range(n_rows):
            _write_wav(audio_dir / f"a{i}.wav", n_samples, seed=i)
            mf.write(f"a{i}.wav\t{n_samples}\n")
    n_labels = int(n_samples / 16000 * label_rate)
    rng = np.random.RandomState(0)
    with open(tmp_path / "train.km", "w") as lf:
        for i in range(n_rows):
            lf.write(" ".join(str(x) for x in
                              rng.randint(0, 16, max(n_labels, 1))) + "\n")


def test_hubert_dataset_and_collator(tmp_path):
    from fengshen_tpu.data.hubert import (HubertCollator, HubertDataset,
                                          conv_frames)
    from fengshen_tpu.models.hubert import HubertConfig
    _hubert_data(tmp_path)
    cfg = HubertConfig.small_test_config()
    ds = HubertDataset(str(tmp_path / "train.tsv"),
                       str(tmp_path / "train.km"))
    assert len(ds) == 4
    s = ds[0]
    assert s["waveform"].ndim == 1 and len(s["cluster_ids"]) > 0
    coll = HubertCollator(cfg.conv_layers, mask_prob=0.5, mask_length=2)
    batch = coll([ds[0], ds[1]])
    frames = conv_frames(4000, cfg.conv_layers)
    assert batch["waveform"].shape == (2, 4000)
    assert batch["cluster_ids"].shape == (2, frames)
    assert batch["mask_time_indices"].any()


def test_hubert_dataset_crop(tmp_path):
    from fengshen_tpu.data.hubert import HubertDataset
    _hubert_data(tmp_path, n_samples=8000)
    ds = HubertDataset(str(tmp_path / "train.tsv"),
                       str(tmp_path / "train.km"),
                       max_sample_size=4000, seed=3)
    s = ds[0]
    assert len(s["waveform"]) == 4000
    assert 0 < len(s["cluster_ids"]) <= 14


def test_pretrain_hubert_e2e(tmp_path, mesh8, monkeypatch):
    from fengshen_tpu.examples.hubert import pretrain_hubert
    from fengshen_tpu.models.hubert import HubertConfig
    _hubert_data(tmp_path, n_rows=8)
    # main() builds HubertConfig() — swap in the small test config
    small = HubertConfig.small_test_config()
    monkeypatch.setattr(pretrain_hubert, "HubertConfig", lambda: small)
    pretrain_hubert.main([
        "--data", str(tmp_path), "--train_batchsize", "2",
        "--max_steps", "2", "--log_every_n_steps", "1",
        "--warmup_steps", "1",
        "--default_root_dir", str(tmp_path / "runs"),
        "--save_ckpt_path", str(tmp_path / "ckpt"),
        "--load_ckpt_path", str(tmp_path / "ckpt"),
        "--max_sample_size", "4000", "--min_sample_size", "10",
        "--seed", "1"])
    lines = [json.loads(l) for l in open(tmp_path / "runs" / "metrics.jsonl")]
    losses = [l["loss"] for l in lines if "loss" in l]
    assert len(losses) == 2 and all(np.isfinite(losses))


# ---------------------------------------------------------------------------
# clip / sd / dreambooth
# ---------------------------------------------------------------------------

def _image_dataset(tmp_path, n=4, size=32):
    pytest.importorskip("PIL")
    from PIL import Image
    img_dir = tmp_path / "imgs"
    img_dir.mkdir(exist_ok=True)
    import csv
    rows = []
    rng = np.random.RandomState(0)
    for i in range(n):
        arr = (rng.rand(size, size, 3) * 255).astype(np.uint8)
        p = img_dir / f"i{i}.png"
        Image.fromarray(arr).save(p)
        rows.append({"image": str(p), "caption": "一张测试图片"})
    csv_path = tmp_path / "data.csv"
    with open(csv_path, "w", newline="") as f:
        w = csv.DictWriter(f, fieldnames=["image", "caption"])
        w.writeheader()
        w.writerows(rows)
    return img_dir, csv_path


def _bert_dir(tmp_path):
    from transformers import BertTokenizer
    from fengshen_tpu.models.bert import BertConfig
    chars = list("一张测试图片的照狗")
    vocab = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"] + sorted(set(chars))
    (tmp_path / "vocab.txt").write_text("\n".join(vocab))
    tok = BertTokenizer(str(tmp_path / "vocab.txt"))
    model_dir = tmp_path / "model"
    model_dir.mkdir(exist_ok=True)
    tok.save_pretrained(str(model_dir))
    BertConfig.small_test_config(vocab_size=len(tok)).save_pretrained(
        str(model_dir))
    return tok, model_dir


def test_pretrain_taiyi_clip_e2e(tmp_path, mesh8, monkeypatch):
    from fengshen_tpu.examples.pretrain_taiyi_clip import pretrain
    from fengshen_tpu.models.clip import CLIPVisionConfig
    _, csv_path = _image_dataset(tmp_path)
    tok, model_dir = _bert_dir(tmp_path)
    small_vision = CLIPVisionConfig.small_test_config(image_size=32)
    monkeypatch.setattr(pretrain, "CLIPVisionConfig", lambda: small_vision)
    pretrain.main([
        "--model_path", str(model_dir), "--train_csv", str(csv_path),
        "--train_batchsize", "2", "--max_steps", "2",
        "--log_every_n_steps", "1", "--warmup_steps", "1",
        "--default_root_dir", str(tmp_path / "runs"),
        "--save_ckpt_path", str(tmp_path / "ckpt"),
        "--load_ckpt_path", str(tmp_path / "ckpt"),
        "--image_size", "32", "--max_length", "16", "--seed", "1",
        "--freeze_image_tower"])
    lines = [json.loads(l) for l in open(tmp_path / "runs" / "metrics.jsonl")]
    losses = [l["loss"] for l in lines if "loss" in l]
    assert len(losses) == 2 and all(np.isfinite(losses))


def _small_sd_patches(monkeypatch, module):
    from fengshen_tpu.models.stable_diffusion.autoencoder_kl import VAEConfig
    from fengshen_tpu.models.stable_diffusion.unet import UNetConfig
    monkeypatch.setattr(module, "VAEConfig",
                        lambda: VAEConfig.small_test_config())
    monkeypatch.setattr(module, "UNetConfig",
                        lambda: UNetConfig.small_test_config())


def test_finetune_taiyi_sd_e2e(tmp_path, mesh8, monkeypatch):
    from fengshen_tpu.examples.finetune_taiyi_stable_diffusion import finetune
    _small_sd_patches(monkeypatch, finetune)
    _, csv_path = _image_dataset(tmp_path)
    tok, model_dir = _bert_dir(tmp_path)
    finetune.main([
        "--model_path", str(model_dir), "--train_csv", str(csv_path),
        "--train_batchsize", "2", "--max_steps", "2",
        "--log_every_n_steps", "1", "--warmup_steps", "1",
        "--default_root_dir", str(tmp_path / "runs"),
        "--save_ckpt_path", str(tmp_path / "ckpt"),
        "--load_ckpt_path", str(tmp_path / "ckpt"),
        "--image_size", "32", "--max_length", "16", "--seed", "1"])
    lines = [json.loads(l) for l in open(tmp_path / "runs" / "metrics.jsonl")]
    losses = [l["loss"] for l in lines if "loss" in l]
    assert len(losses) == 2 and all(np.isfinite(losses))


def test_dreambooth_e2e_with_prior(tmp_path, mesh8, monkeypatch):
    from fengshen_tpu.examples.stable_diffusion_dreambooth import train
    from fengshen_tpu.examples.finetune_taiyi_stable_diffusion import finetune
    _small_sd_patches(monkeypatch, finetune)
    pytest.importorskip("PIL")
    from PIL import Image
    rng = np.random.RandomState(0)
    for d in ("instance", "cls"):
        (tmp_path / d).mkdir()
        for i in range(4):
            arr = (rng.rand(32, 32, 3) * 255).astype(np.uint8)
            Image.fromarray(arr).save(tmp_path / d / f"{i}.png")
    tok, model_dir = _bert_dir(tmp_path)
    train.main([
        "--model_path", str(model_dir),
        "--instance_data_dir", str(tmp_path / "instance"),
        "--instance_prompt", "一张照片的狗",
        "--class_data_dir", str(tmp_path / "cls"),
        "--class_prompt", "一张照片", "--with_prior_preservation",
        "--prior_loss_weight", "0.5",
        "--train_batchsize", "2", "--max_steps", "2",
        "--log_every_n_steps", "1", "--warmup_steps", "1",
        "--default_root_dir", str(tmp_path / "runs"),
        "--save_ckpt_path", str(tmp_path / "ckpt"),
        "--load_ckpt_path", str(tmp_path / "ckpt"),
        "--image_size", "32", "--max_length", "16", "--seed", "1"])
    lines = [json.loads(l) for l in open(tmp_path / "runs" / "metrics.jsonl")]
    losses = [l["loss"] for l in lines if "loss" in l]
    assert len(losses) == 2 and all(np.isfinite(losses))


def test_dreambooth_dataset_pairs(tmp_path):
    from fengshen_tpu.examples.stable_diffusion_dreambooth.train import (
        DreamBoothDataset)
    pytest.importorskip("PIL")
    from PIL import Image
    (tmp_path / "inst").mkdir()
    (tmp_path / "cls").mkdir()
    arr = np.zeros((8, 8, 3), np.uint8)
    for i in range(3):
        Image.fromarray(arr).save(tmp_path / "inst" / f"{i}.png")
    Image.fromarray(arr).save(tmp_path / "cls" / "c.png")
    ds = DreamBoothDataset(str(tmp_path / "inst"), "sks 狗",
                           str(tmp_path / "cls"), "狗")
    assert len(ds) == 3
    s = ds[1]
    assert s["instance_prompt"] == "sks 狗" and "class_image" in s


def test_clip_finetune_flickr_e2e(tmp_path, mesh8, monkeypatch):
    """The finetune driver injects the reference presets (LR table, ViT
    AdamW betas/eps, wd 0.2, cosine) and trains BOTH towers."""
    from fengshen_tpu.examples.clip_finetune import clip_finetune_flickr
    from fengshen_tpu.examples.pretrain_taiyi_clip import pretrain
    from fengshen_tpu.models.clip import CLIPVisionConfig
    _, csv_path = _image_dataset(tmp_path)
    tok, model_dir = _bert_dir(tmp_path)
    small_vision = CLIPVisionConfig.small_test_config(image_size=32)
    monkeypatch.setattr(pretrain, "CLIPVisionConfig", lambda: small_vision)

    seen = {}
    orig = pretrain.main

    def spy(argv):
        seen["argv"] = list(argv)
        return orig(argv)

    monkeypatch.setattr(pretrain, "main", spy)
    clip_finetune_flickr.main([
        "--model_path", str(model_dir), "--train_csv", str(csv_path),
        "--train_batchsize", "2", "--max_steps", "2",
        "--log_every_n_steps", "1", "--warmup_steps", "1",
        "--default_root_dir", str(tmp_path / "runs"),
        "--save_ckpt_path", str(tmp_path / "ckpt"),
        "--load_ckpt_path", str(tmp_path / "ckpt"),
        "--image_size", "32", "--max_length", "16", "--seed", "1",
        "--learning_rate", "1e-4"])  # explicit flag beats the preset
    argv = seen["argv"]
    assert "--freeze_image_tower" not in argv
    assert argv[argv.index("--learning_rate") + 1] == "1e-4"
    assert argv[argv.index("--weight_decay") + 1] == "0.2"
    assert argv[argv.index("--scheduler_type") + 1] == "cosine"
    lines = [json.loads(l)
             for l in open(tmp_path / "runs" / "metrics.jsonl")]
    losses = [l["loss"] for l in lines if "loss" in l]
    assert len(losses) == 2 and all(np.isfinite(losses))


@pytest.mark.slow
def test_dreambooth_class_image_pregeneration(tmp_path, mesh8,
                                              monkeypatch):
    """--num_class_images tops up class_data_dir by sampling the frozen
    model before training (reference train_with_prior.sh recipe)."""
    import glob

    from fengshen_tpu.examples.stable_diffusion_dreambooth import train
    from fengshen_tpu.examples.finetune_taiyi_stable_diffusion import (
        finetune)
    _small_sd_patches(monkeypatch, finetune)
    pytest.importorskip("PIL")
    from PIL import Image
    rng = np.random.RandomState(0)
    (tmp_path / "instance").mkdir()
    for i in range(2):
        arr = (rng.rand(32, 32, 3) * 255).astype(np.uint8)
        Image.fromarray(arr).save(tmp_path / "instance" / f"{i}.png")
    (tmp_path / "cls").mkdir()  # EMPTY: everything must be generated
    tok, model_dir = _bert_dir(tmp_path)
    train.main([
        "--model_path", str(model_dir),
        "--instance_data_dir", str(tmp_path / "instance"),
        "--instance_prompt", "一张照片的狗",
        "--class_data_dir", str(tmp_path / "cls"),
        "--class_prompt", "一张照片", "--with_prior_preservation",
        "--num_class_images", "2", "--class_gen_steps", "2",
        "--train_batchsize", "2", "--max_steps", "1",
        "--log_every_n_steps", "1", "--warmup_steps", "1",
        "--default_root_dir", str(tmp_path / "runs"),
        "--save_ckpt_path", str(tmp_path / "ckpt"),
        "--load_ckpt_path", str(tmp_path / "ckpt"),
        "--image_size", "32", "--max_length", "16", "--seed", "1"])
    generated = glob.glob(str(tmp_path / "cls" / "class_gen_*.png"))
    assert len(generated) == 2
    lines = [json.loads(l)
             for l in open(tmp_path / "runs" / "metrics.jsonl")]
    assert any("loss" in l for l in lines)


@pytest.mark.slow
def test_uniex_train_mode_e2e(tmp_path, mesh8):
    """uniex example --train: finetune on spandata jsonl then predict to
    --output_path (the uniex train.sh/predict.sh surface)."""
    from fengshen_tpu.examples.uniex import example as uniex_example

    rows = [{"task_type": "实体识别",
             "text": "小明在北京工作",
             "choices": [{"entity_type": "人物姓名",
                          "entity_list": [{"entity_name": "小明"}]},
                         {"entity_type": "地址",
                          "entity_list": [{"entity_name": "北京"}]}],
             "id": i} for i in range(4)]
    train_file = tmp_path / "train.json"
    with open(train_file, "w") as f:
        for r in rows:
            f.write(json.dumps(r, ensure_ascii=False) + "\n")
    _, model_dir = _bert_dir(tmp_path)
    out = tmp_path / "predict.json"
    result = uniex_example.main([
        "--model_path", str(model_dir),
        "--train", "--train_file", str(train_file),
        "--test_file", str(train_file),
        "--output_path", str(out),
        "--max_length", "64", "--max_entity_types", "4",
        "--train_batchsize", "2", "--max_steps", "2", "--max_epochs", "1",
        "--default_root_dir", str(tmp_path / "runs"),
        "--save_ckpt_path", str(tmp_path / "ckpt"),
        "--load_ckpt_path", str(tmp_path / "ckpt"),
        "--precision", "fp32"])
    assert len(result) == 4
    lines = [json.loads(x) for x in open(out, encoding="utf-8")]
    assert len(lines) == 4
