"""Regression guard for SPMD compilation hazards (VERDICT r2 item 1).

Round 2's 8-device dryrun log carried two XLA warnings — "Involuntary full
rematerialization ... SPMD will replicate the tensor" — on the vocab-sharded
embedding gather: a plain `take` on a P("tensor","fsdp") table forces XLA to
all-gather the full table every step on a real pod. The fix is the
vocab-parallel lookup (masked local take + psum over the vocab shards,
mirroring the reference's VocabParallelEmbedding,
reference: fengshen/models/megatron/mpu/layers.py:55-130).

This test compiles the SAME fsdp+tensor-sharded train step the driver's
dryrun runs and fails if any "Involuntary full rematerialization" warning
comes back — XLA prints it from the C++ SPMD partitioner, so we capture at
the file-descriptor level (pytest's capfd).
"""

import argparse
import json

import numpy as np
import pytest


def _fit_sharded_llama(tmp_path, capfd):
    from fengshen_tpu.data import UniversalDataModule
    from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from fengshen_tpu.models.model_utils import add_module_args
    from fengshen_tpu.parallel import set_mesh
    from fengshen_tpu.trainer import Trainer, add_trainer_args
    from fengshen_tpu.trainer.modules import CausalLMModule

    parser = argparse.ArgumentParser()
    add_module_args(parser)
    add_trainer_args(parser)
    UniversalDataModule.add_data_specific_args(parser)
    args = parser.parse_args([
        "--max_steps", "1", "--train_batchsize", "4",
        "--data_parallel_size", "1", "--fsdp_parallel_size", "2",
        "--sequence_parallel_size", "2",
        "--tensor_model_parallel_size", "2",
        "--log_every_n_steps", "1", "--warmup_steps", "1",
        "--default_root_dir", str(tmp_path)])

    config = LlamaConfig(
        vocab_size=512, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4,
        max_position_embeddings=64, dtype="float32",
        attention_impl="ring")
    model = LlamaForCausalLM(config)
    rng = np.random.RandomState(0)
    rows = [{"input_ids": rng.randint(0, 511, 32).tolist()}
            for _ in range(8)]

    class ListDS:
        def __len__(self):
            return len(rows)

        def __getitem__(self, i):
            return rows[i]

    capfd.readouterr()  # drop anything buffered before compilation
    trainer = Trainer(args)
    module = CausalLMModule(args, model, config)
    dm = UniversalDataModule(args=args, datasets={"train": ListDS()})
    state = trainer.fit(module, dm)
    set_mesh(None)
    captured = capfd.readouterr()
    return state, captured.err + captured.out


@pytest.mark.slow
def test_sharded_train_step_has_no_involuntary_rematerialization(
        tmp_path, capfd):
    state, log = _fit_sharded_llama(tmp_path, capfd)
    assert int(state.step) == 1
    assert "Involuntary full rematerialization" not in log, (
        "the compiled fsdp+tp train step reintroduced an SPMD "
        "full-rematerialization (likely the embedding lookup):\n" +
        "\n".join(l for l in log.splitlines()
                  if "rematerialization" in l.lower()))
    lines = [json.loads(l) for l in open(tmp_path / "metrics.jsonl")]
    losses = [l["loss"] for l in lines if "loss" in l]
    assert losses and all(np.isfinite(losses))


@pytest.mark.slow
def test_13b_shape_partition_compiles_without_spec_drops(mesh8, caplog):
    """Compile-only (AOT lower+compile on ShapeDtypeStructs — no 52 GB
    of real buffers) pass of the REAL 13B-shape partition layout on the
    8-device CPU mesh (VERDICT r3 weak #4): catches divisibility/layout
    hazards of the production partition rules that the toy-shape dryrun
    cannot, and asserts no `_spec_fits` fallback silently replicated a
    parameter (VERDICT r3 weak #3)."""
    import logging

    import jax
    import jax.numpy as jnp

    from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from fengshen_tpu.models.model_utils import add_module_args
    from fengshen_tpu.parallel import partition
    from fengshen_tpu.parallel.partition import (make_shardings,
                                                 shard_batch_spec)
    from fengshen_tpu.trainer import add_trainer_args
    from fengshen_tpu.trainer.modules import CausalLMModule

    parser = argparse.ArgumentParser()
    add_module_args(parser)
    add_trainer_args(parser)
    args = parser.parse_args(["--precision", "bf16"])

    # the BENCH_CONFIG=large ladder shape (bench.py): Ziya-LLaMA-13B dims
    config = LlamaConfig(
        vocab_size=32000, hidden_size=5120, intermediate_size=13824,
        num_hidden_layers=40, num_attention_heads=40,
        num_key_value_heads=8, max_position_embeddings=2048,
        dtype="bfloat16", param_dtype="bfloat16", scan_layers=True,
        gradient_checkpointing=True, remat_policy="dots_no_batch")
    model = LlamaForCausalLM(config)
    module = CausalLMModule(args, model, config)

    rng = jax.random.PRNGKey(0)
    params_struct = jax.eval_shape(module.init_params, rng)
    n_params = sum(np.prod(l.shape) for l in
                   jax.tree_util.tree_leaves(params_struct))
    assert n_params > 1.0e10, f"not a 13B-shape model: {n_params:.2e}"

    batch_struct = {
        "input_ids": jax.ShapeDtypeStruct((4, 2048), jnp.int32),
        "labels": jax.ShapeDtypeStruct((4, 2048), jnp.int32)}

    partition._SPEC_FIT_WARNED.clear()
    caplog.set_level(logging.WARNING, logger="fengshen_tpu.parallel")
    param_sh = make_shardings(module.partition_rules(), params_struct,
                              mesh8)
    batch_sh = jax.tree_util.tree_map(
        lambda s: jax.sharding.NamedSharding(
            mesh8, shard_batch_spec(len(s.shape))), batch_struct)

    def loss_fn(params, batch, rng):
        return module.training_loss(params, batch, rng)

    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)
    step = jax.jit(grad_fn, in_shardings=(param_sh, batch_sh, None))
    compiled = step.lower(params_struct, batch_struct, rng).compile()
    assert compiled is not None

    # every parameter dim the rules shard must divide the real 13B dims
    drops = [r.message for r in caplog.records
             if "REPLICATING" in r.message]
    assert not drops, f"13B-shape partition silently degraded: {drops}"


def test_spec_fits_warns_once_per_param(mesh8, caplog):
    """VERDICT r3 weak #3: a non-divisible NAMED parameter dim must warn
    (once), activation constraints must stay silent."""
    import logging

    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from fengshen_tpu.parallel import partition
    from fengshen_tpu.parallel.partition import make_shardings

    partition._SPEC_FIT_WARNED.clear()
    caplog.set_level(logging.WARNING, logger="fengshen_tpu.parallel")
    tree = {"w": jax.ShapeDtypeStruct((6, 6), jnp.float32)}  # 6 % 4 != 0
    rules = [("w", P(("data", "fsdp"), "tensor")), (".*", P(None))]
    make_shardings(rules, tree, mesh8)
    warned = [r for r in caplog.records if "REPLICATING" in r.message]
    assert len(warned) == 1 and "w" in warned[0].message
    # second call: already warned, stays quiet
    caplog.clear()
    make_shardings(rules, tree, mesh8)
    assert not [r for r in caplog.records if "REPLICATING" in r.message]
    # anonymous (activation-constraint) fits never warn
    caplog.clear()
    partition._spec_fits(P(("data", "fsdp")), mesh8, (6,))
    assert not [r for r in caplog.records if "REPLICATING" in r.message]
