"""Regression guard for SPMD compilation hazards (VERDICT r2 item 1).

Round 2's 8-device dryrun log carried two XLA warnings — "Involuntary full
rematerialization ... SPMD will replicate the tensor" — on the vocab-sharded
embedding gather: a plain `take` on a P("tensor","fsdp") table forces XLA to
all-gather the full table every step on a real pod. The fix is the
vocab-parallel lookup (masked local take + psum over the vocab shards,
mirroring the reference's VocabParallelEmbedding,
reference: fengshen/models/megatron/mpu/layers.py:55-130).

This test compiles the SAME fsdp+tensor-sharded train step the driver's
dryrun runs and fails if any "Involuntary full rematerialization" warning
comes back — XLA prints it from the C++ SPMD partitioner, so we capture at
the file-descriptor level (pytest's capfd).
"""

import argparse
import json

import numpy as np
import pytest


def _fit_sharded_llama(tmp_path, capfd):
    from fengshen_tpu.data import UniversalDataModule
    from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from fengshen_tpu.models.model_utils import add_module_args
    from fengshen_tpu.parallel import set_mesh
    from fengshen_tpu.trainer import Trainer, add_trainer_args
    from fengshen_tpu.trainer.modules import CausalLMModule

    parser = argparse.ArgumentParser()
    add_module_args(parser)
    add_trainer_args(parser)
    UniversalDataModule.add_data_specific_args(parser)
    args = parser.parse_args([
        "--max_steps", "1", "--train_batchsize", "4",
        "--data_parallel_size", "1", "--fsdp_parallel_size", "2",
        "--sequence_parallel_size", "2",
        "--tensor_model_parallel_size", "2",
        "--log_every_n_steps", "1", "--warmup_steps", "1",
        "--default_root_dir", str(tmp_path)])

    config = LlamaConfig(
        vocab_size=512, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4,
        max_position_embeddings=64, dtype="float32",
        attention_impl="ring")
    model = LlamaForCausalLM(config)
    rng = np.random.RandomState(0)
    rows = [{"input_ids": rng.randint(0, 511, 32).tolist()}
            for _ in range(8)]

    class ListDS:
        def __len__(self):
            return len(rows)

        def __getitem__(self, i):
            return rows[i]

    capfd.readouterr()  # drop anything buffered before compilation
    trainer = Trainer(args)
    module = CausalLMModule(args, model, config)
    dm = UniversalDataModule(args=args, datasets={"train": ListDS()})
    state = trainer.fit(module, dm)
    set_mesh(None)
    captured = capfd.readouterr()
    return state, captured.err + captured.out


@pytest.mark.slow
def test_sharded_train_step_has_no_involuntary_rematerialization(
        tmp_path, capfd):
    state, log = _fit_sharded_llama(tmp_path, capfd)
    assert int(state.step) == 1
    assert "Involuntary full rematerialization" not in log, (
        "the compiled fsdp+tp train step reintroduced an SPMD "
        "full-rematerialization (likely the embedding lookup):\n" +
        "\n".join(l for l in log.splitlines()
                  if "rematerialization" in l.lower()))
    lines = [json.loads(l) for l in open(tmp_path / "metrics.jsonl")]
    losses = [l["loss"] for l in lines if "loss" in l]
    assert losses and all(np.isfinite(losses))
