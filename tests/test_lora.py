"""LoRA adapters (ops/lora.py + trainer LoraTrainModule).

Reference surface: the merge CLI fs_merge_weight.py and the roadmap
item ziya_llama/README.md:59. Contracts tested: zero-init B makes the
merged forward EQUAL the base forward; training moves only the
adapters; adam moments exist only for the adapters; the merge CLI
produces a plain checkpoint whose forward equals the adapted model.
"""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from fengshen_tpu.ops.lora import (apply_lora, init_lora,
                                   lora_param_labels, merge_lora)

pytestmark = pytest.mark.slow


def _base(scan=False, layers=2):
    cfg = LlamaConfig(vocab_size=89, hidden_size=32, intermediate_size=64,
                      num_hidden_layers=layers, num_attention_heads=4,
                      max_position_embeddings=64, dtype="float32",
                      scan_layers=scan)
    model = LlamaForCausalLM(cfg)
    ids = jnp.asarray(np.random.RandomState(0).randint(1, 88, (2, 12)),
                      jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids[:, :4])["params"]
    return cfg, model, params, ids


@pytest.mark.parametrize("scan", [False, True])
def test_lora_init_is_identity_and_targets_match(scan):
    """Zero-init B: merged == base bit-for-bit; adapters exist exactly
    on the targeted kernels (incl. the 3-D scan_layers stacks)."""
    cfg, model, params, ids = _base(scan=scan)
    lora = init_lora(params, jax.random.PRNGKey(1), rank=4,
                     target_regex=r"(q_proj|v_proj)")
    merged = apply_lora(params, lora)
    ref = model.apply({"params": params}, ids)
    out = model.apply({"params": merged}, ids)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))

    flat = {"/".join(str(getattr(k, "key", k)) for k in p): leaf
            for p, leaf in jax.tree_util.tree_flatten_with_path(lora)[0]}
    a_keys = [k for k in flat if k.endswith("lora_a")]
    assert a_keys and all(("q_proj" in k or "v_proj" in k)
                          for k in a_keys)
    for k in a_keys:
        if scan:  # stacked per-layer adapters
            assert flat[k].ndim == 3 and flat[k].shape[0] == \
                cfg.num_hidden_layers and flat[k].shape[-1] == 4
        else:
            assert flat[k].shape == (32, 4)


def test_lora_delta_math():
    """With a nonzero B the merged kernel is exactly
    W + (alpha/rank) * A @ B; untargeted kernels stay untouched."""
    _, _, params, _ = _base()
    lora = init_lora(params, jax.random.PRNGKey(1), rank=2,
                     target_regex=r"q_proj", alpha=8.0)

    def bump(l):
        if isinstance(l, dict) and "lora_b" in l:
            return {**l, "lora_b": jnp.ones_like(l["lora_b"])}
        return {k: bump(v) for k, v in l.items()}

    lora = bump(lora)
    merged = merge_lora(params, lora)
    attn = params["model"]["layers_0"]["self_attn"]
    attn_m = merged["model"]["layers_0"]["self_attn"]
    l_attn = lora["model"]["layers_0"]["self_attn"]
    want = np.asarray(attn["q_proj"]["kernel"]) + 4.0 * (
        np.asarray(l_attn["q_proj"]["lora_a"], np.float32)
        @ np.ones((2, 32), np.float32))
    np.testing.assert_allclose(np.asarray(attn_m["q_proj"]["kernel"]),
                               want, rtol=1e-6)
    np.testing.assert_array_equal(
        np.asarray(attn_m["k_proj"]["kernel"]),
        np.asarray(attn["k_proj"]["kernel"]))


def test_lora_train_modules_head_trains():
    """The modules_to_save analog: with train_regex the task head gets
    REAL gradients (not stop_gradient'ed) and adamw updates, the
    backbone stays bit-frozen, and the adapters train — the exact
    interplay that silently broke when the whole base was
    stop_gradient'ed."""
    import optax
    from flax import linen as nn

    from fengshen_tpu.trainer.modules import LoraTrainModule
    from fengshen_tpu.trainer.module import TrainModule

    class Net(nn.Module):
        @nn.compact
        def __call__(self, x):
            h = nn.Dense(8, name="backbone_q_proj")(x)
            return nn.Dense(3, name="cls_layer")(h)

    import argparse

    from fengshen_tpu.models.model_utils import add_module_args

    margs = add_module_args(argparse.ArgumentParser()).parse_args(
        ["--learning_rate", "1e-2"])

    class Inner(TrainModule):
        def __init__(self):
            super().__init__(margs)
            self.net = Net()

        def init_params(self, rng):
            return self.net.init(rng, jnp.zeros((1, 4)))["params"]

        def training_loss(self, params, batch, rng):
            out = self.net.apply({"params": params}, batch["x"])
            return jnp.mean((out - batch["y"]) ** 2), {}

    mod = LoraTrainModule(Inner(), rank=2,
                          target_regex="backbone_q_proj",
                          train_regex="cls_layer")
    params = mod.init_params(jax.random.PRNGKey(0))
    tx, _ = mod.configure_optimizers(10, params)
    opt = tx.init(params)
    batch = {"x": jnp.ones((2, 4)), "y": jnp.ones((2, 3))}

    p = params
    for _ in range(2):
        grads = jax.grad(
            lambda q: mod.training_loss(q, batch, None)[0])(p)
        upd, opt = tx.update(grads, opt, p)
        p = optax.apply_updates(p, upd)

    base0, base1 = params["base"], p["base"]
    # head trained
    assert np.abs(np.asarray(base1["cls_layer"]["kernel"]) -
                  np.asarray(base0["cls_layer"]["kernel"])).max() > 0
    # backbone bit-frozen
    np.testing.assert_array_equal(
        np.asarray(base1["backbone_q_proj"]["kernel"]),
        np.asarray(base0["backbone_q_proj"]["kernel"]))
    # adapters trained
    assert np.abs(np.asarray(
        p["lora"]["backbone_q_proj"]["lora_b"])).max() > 0


def test_lora_classification_e2e(tmp_path, mesh8):
    """finetune_classification --lora_rank: second family (MegatronBert
    naming) through the SAME wrapper — train, validate, and PREDICT
    (exercises predict_step forwarding through the merge) end-to-end."""
    import json as _json

    from tests.test_classification_port import (_write_model_dir,
                                                _write_task_dir)
    from fengshen_tpu.examples.classification import (
        finetune_classification as fc)

    data_dir = _write_task_dir(tmp_path)
    model_dir = _write_model_dir(tmp_path, model_type="megatron-bert")
    out = tmp_path / "pred.json"
    fc.main([
        "--data_dir", str(data_dir), "--train_data", "train.json",
        "--valid_data", "dev.json", "--test_data", "test.json",
        "--pretrained_model_path", str(model_dir),
        "--model_type", "huggingface-megatron_bert",
        "--texta_name", "sentence1", "--textb_name", "sentence2",
        "--max_length", "32", "--train_batchsize", "4",
        "--valid_batchsize", "4", "--max_epochs", "1",
        "--learning_rate", "1e-3", "--lora_rank", "2",
        "--output_save_path", str(out),
        "--default_root_dir", str(tmp_path / "runs"),
        "--precision", "fp32"])
    lines = [_json.loads(x) for x in open(str(out) + ".0")]
    assert len(lines) == 6
    assert sorted(l["id"] for l in lines) == list(range(6))


def test_lora_summary_seq2seq_e2e(tmp_path, mesh8):
    """Third archetype — encoder-decoder (T5) through the summary
    driver: --lora_rank trains, then the rouge predict path decodes
    through the wrapper's predict_step."""
    import json as _json

    from tests.test_examples_batch2 import (_bert_tokenizer_dir,
                                            _write_jsonl)
    from fengshen_tpu.examples.summary import seq2seq_summary
    from fengshen_tpu.models.t5 import T5Config

    tok, model_dir = _bert_tokenizer_dir(tmp_path)
    T5Config.small_test_config(vocab_size=len(tok)).save_pretrained(
        str(model_dir))
    rows = [{"text": "今天天气很好我们去公园吧然后回家",
             "summary": "天气很好"}] * 8
    _write_jsonl(tmp_path / "train.json", rows)
    _write_jsonl(tmp_path / "test.json", rows[:4])
    out = tmp_path / "predict.json"
    seq2seq_summary.main([
        "--model_type", "t5", "--model_path", str(model_dir),
        "--train_file", str(tmp_path / "train.json"),
        "--test_file", str(tmp_path / "test.json"),
        "--train_batchsize", "4", "--test_batchsize", "2",
        "--max_steps", "2", "--max_enc_length", "16",
        "--max_dec_length", "8", "--learning_rate", "1e-3",
        "--warmup_steps", "1", "--lora_rank", "2",
        "--output_save_path", str(out),
        "--default_root_dir", str(tmp_path / "runs"),
        "--save_ckpt_path", str(tmp_path / "ckpt"),
        "--load_ckpt_path", str(tmp_path / "ckpt"),
        "--precision", "fp32"])
    lines = [_json.loads(x) for x in open(out, encoding="utf-8")]
    assert len(lines) == 4 and all("pred" in r for r in lines)


def test_lora_trainer_e2e_and_merge_cli(tmp_path, mesh8):
    """finetune_ziya_llama --lora_rank: the base stays FROZEN, the
    adapters move, adam moments exist only for the adapters, and the
    merge CLI writes a plain checkpoint whose params equal
    merge_lora(base, lora)."""
    import unittest.mock as mock

    import orbax.checkpoint as ocp

    from fengshen_tpu.examples.ziya_llama import finetune_ziya_llama
    from fengshen_tpu.ops import lora as lora_cli

    model_dir = tmp_path / "model"
    model_dir.mkdir()

    class CharTok:
        pad_token_id = 0
        eos_token_id = 2

        def encode(self, text, add_special_tokens=True):
            ids = [min(3 + (ord(c) % 90), 95) for c in text]
            return ([1] + ids) if add_special_tokens else ids

        @classmethod
        def from_pretrained(cls, path):
            return cls()

    cfg = LlamaConfig(vocab_size=128, hidden_size=32,
                      intermediate_size=64, num_hidden_layers=2,
                      num_attention_heads=4, max_position_embeddings=64,
                      dtype="float32", param_dtype="float32")
    cfg.save_pretrained(str(model_dir))
    train = tmp_path / "sft.json"
    with open(train, "w") as f:
        for i in range(8):
            f.write(json.dumps({"query": "你好" * (1 + i % 3),
                                "answer": "hello"},
                               ensure_ascii=False) + "\n")

    ckpt_dir = tmp_path / "ckpt"
    with mock.patch("transformers.AutoTokenizer.from_pretrained",
                    CharTok.from_pretrained):
        finetune_ziya_llama.main([
            "--model_path", str(model_dir), "--train_file", str(train),
            "--train_batchsize", "4", "--max_steps", "2",
            "--max_seq_length", "32", "--log_every_n_steps", "1",
            "--warmup_steps", "1", "--learning_rate", "1e-2",
            "--lora_rank", "2", "--every_n_train_steps", "2",
            "--default_root_dir", str(tmp_path / "runs"),
            "--save_ckpt_path", str(ckpt_dir),
            "--load_ckpt_path", str(ckpt_dir),
            "--seed", "1"])

    mgr = ocp.CheckpointManager(str(ckpt_dir.resolve()))
    step = mgr.latest_step()
    assert step == 2
    payload = mgr.restore(step)["state"]
    params = payload["params"]
    assert set(params) == {"base", "lora"}

    # adapters moved and moments exist only for them (the optimizer
    # masking that freezes the base)
    b_leaves = {("/".join(str(getattr(k, "key", k)) for k in p)): leaf
                for p, leaf in
                jax.tree_util.tree_flatten_with_path(
                    params["lora"])[0]}
    assert any(np.abs(v).sum() > 0 for k, v in b_leaves.items()
               if k.endswith("lora_b"))  # adapters trained
    mu_leaves = [
        "/".join(str(getattr(k, "key", k)) for k in p)
        for p, _ in jax.tree_util.tree_flatten_with_path(
            payload["opt_state"])[0]
        if "/mu/" in "/".join(str(getattr(k, "key", k)) for k in p)]
    assert mu_leaves and all(
        l.endswith(("lora_a", "lora_b")) for l in mu_leaves)

    # merge CLI -> plain checkpoint == merge_lora(base, lora)
    out_dir = tmp_path / "merged"
    lora_cli.main(["--input_path", str(ckpt_dir),
                   "--output_path", str(out_dir),
                   "--config_path", str(model_dir)])
    restored = ocp.StandardCheckpointer().restore(
        str(out_dir.resolve() / "params"))
    want = merge_lora(params["base"], params["lora"])
    for (p1, a), (p2, b) in zip(
            jax.tree_util.tree_flatten_with_path(restored)[0],
            jax.tree_util.tree_flatten_with_path(want)[0]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6,
                                   err_msg=jax.tree_util.keystr(p1))
    assert os.path.exists(out_dir / "config.json")
