"""BART golden-value parity vs HF torch."""

import jax.numpy as jnp
import numpy as np
import pytest

from fengshen_tpu.models.bart import BartConfig, BartForConditionalGeneration
from fengshen_tpu.models.bart.convert import torch_to_params


def test_bart_forward_parity():
    torch = pytest.importorskip("torch")
    import transformers
    hf_cfg = transformers.BartConfig(
        vocab_size=128, d_model=32, encoder_layers=2, decoder_layers=2,
        encoder_attention_heads=4, decoder_attention_heads=4,
        encoder_ffn_dim=64, decoder_ffn_dim=64,
        max_position_embeddings=64, attn_implementation="eager")
    torch.manual_seed(0)
    tm = transformers.BartForConditionalGeneration(hf_cfg).eval()
    cfg = BartConfig(vocab_size=128, d_model=32, encoder_layers=2,
                     decoder_layers=2, encoder_attention_heads=4,
                     decoder_attention_heads=4, encoder_ffn_dim=64,
                     decoder_ffn_dim=64, max_position_embeddings=64,
                     dtype="float32")
    params = torch_to_params(tm.state_dict(), cfg)
    enc_ids = np.array([[0, 17, 9, 42, 2]], dtype=np.int32)
    dec_ids = np.array([[2, 0, 17, 9]], dtype=np.int32)
    mask = np.array([[1, 1, 1, 1, 1]], dtype=np.int32)
    logits = BartForConditionalGeneration(cfg).apply(
        {"params": params}, jnp.asarray(enc_ids), jnp.asarray(dec_ids),
        attention_mask=jnp.asarray(mask))
    with torch.no_grad():
        ref = tm(input_ids=torch.tensor(enc_ids, dtype=torch.long),
                 attention_mask=torch.tensor(mask, dtype=torch.long),
                 decoder_input_ids=torch.tensor(dec_ids, dtype=torch.long)
                 ).logits.numpy()
    np.testing.assert_allclose(np.asarray(logits), ref, atol=2e-3)
