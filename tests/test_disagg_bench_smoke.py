"""`make serve-bench-disagg` harness guard (ISSUE 13): the disagg
bench must emit its one BENCH-schema JSON line — with the phase
topology in the row, part of benchdiff's comparison identity — the
disagg rung must beat (or at worst match) the homogeneous 3-replica
baseline, and the fallback rung (decode tier declines every adoption)
must finish with zero client-visible errors and every request counted
as a local fallback.

The fast lane runs the harness in FAKE mode: in-process stdlib phase
replicas with a deterministic token function, a per-prefill chip lock,
and a prefill/decode interference penalty on both-phase replicas — so
the whole flow (homogeneous baseline → phase split through the REAL
router's placement + redirect/collect → decline-everything fallback)
runs in a couple of seconds without a model. The real-subprocess mode
(actual KV handoffs between continuous engines) is the slow lane.
"""

import io
import json
import os
from contextlib import redirect_stdout

import pytest

FAKE = {"DISAGG_BENCH_FAKE": "1", "DISAGG_BENCH_PREFILL": "2",
        "DISAGG_BENCH_DECODE": "2", "DISAGG_BENCH_HOMOGENEOUS": "3",
        "DISAGG_BENCH_REQUESTS": "24",
        "DISAGG_BENCH_FAKE_TOKEN_S": "0.005"}


def _run(monkeypatch, env: dict, base: dict = FAKE) -> dict:
    from fengshen_tpu.disagg import bench

    for key in list(os.environ):
        if key.startswith(("DISAGG_BENCH_", "FLEET_BENCH_",
                           "BENCH_DEGRADED")):
            monkeypatch.delenv(key)
    for key, val in {**base, **env}.items():
        monkeypatch.setenv(key, val)
    out = io.StringIO()
    with redirect_stdout(out):
        bench.main([])
    lines = [l for l in out.getvalue().splitlines()
             if l.startswith("{")]
    assert lines, out.getvalue()
    return json.loads(lines[-1])


def test_disagg_bench_fake_schema_and_rungs(monkeypatch):
    row = _run(monkeypatch, {})
    assert set(row) >= {"metric", "value", "unit", "vs_baseline",
                        "replicas", "topology", "router_topology",
                        "homogeneous_replicas", "fallback", "requests",
                        "fake"}
    assert row["metric"] == "disagg_tokens_per_sec"
    assert row["unit"] == "tokens/s"
    assert row["value"] > 0 and row["tokens_per_sec_homogeneous"] > 0
    # the comparison identity benchdiff keys on: replica count AND
    # phase topology (never diffed against a homogeneous row)
    assert row["replicas"] == 4
    assert row["topology"] == "prefill=2,decode=2"
    # the router itself saw the split (phases flowed through /stats)
    assert row["router_topology"] == "prefill=2,decode=2"
    assert row["fake"] is True and row["backend"] == "fake"
    # the acceptance bar: disagg ≥ homogeneous at comparable capacity
    # (the fake cost model gives it a real interference edge, so the
    # loose timing bar stays well clear of flake territory)
    assert row["vs_baseline"] >= 1.0, row
    # zero failures in either measured rung; every disagg request went
    # through a REAL router redirect, token-identical to homogeneous
    assert row["failed"] == 0
    assert row["redirects"] == row["requests"]
    assert row["token_identical_disagg_vs_homogeneous"] is True
    # the fallback rung: decode tier declines EVERY adoption — all
    # requests still answer via local prefill-and-decode, counted
    fb = row["fallback"]
    assert fb["enabled"] is True
    assert fb["failed"] == 0
    assert fb["completed"] == row["requests"]
    assert fb["fallbacks"] == row["requests"]
    assert fb["declined"] >= row["requests"]
    assert fb["token_identical"] is True
    assert "degraded" not in row


def test_disagg_bench_fleet_env_fallback(monkeypatch):
    """DISAGG_BENCH_* knobs fall back to FLEET_BENCH_* so one CI env
    block can steer both benches."""
    row = _run(monkeypatch,
               {"FLEET_BENCH_REQUESTS": "6",
                "FLEET_BENCH_FAKE": "1"},
               base={"DISAGG_BENCH_PREFILL": "1",
                     "DISAGG_BENCH_DECODE": "1",
                     "DISAGG_BENCH_HOMOGENEOUS": "2"})
    assert row["requests"] == 6
    assert row["fake"] is True
    assert row["topology"] == "prefill=1,decode=1"
    assert row["failed"] == 0


def test_disagg_bench_degraded_flag(monkeypatch):
    row = _run(monkeypatch, {"BENCH_DEGRADED": "1",
                             "DISAGG_BENCH_REQUESTS": "6"})
    assert row["degraded"] is True


@pytest.mark.slow
def test_disagg_bench_real_handoffs_zero_failed(monkeypatch):
    """The real path: replica subprocesses (random-init llama,
    continuous engines with DisaggCoordinators) behind the real router
    — every request completes through an actual KV handoff or a
    counted local fallback, zero failures, token-identical to the
    homogeneous fleet. ~minutes on CPU."""
    row = _run(monkeypatch,
               {"DISAGG_BENCH_BASE_PORT": "8460",
                "DISAGG_BENCH_REQUESTS": "12"}, base={})
    assert row["fake"] is False
    assert row["topology"] == "prefill=2,decode=2"
    assert row["failed"] == 0
    assert row["token_identical_disagg_vs_homogeneous"] is True, row
