"""DeBERTa-v2 golden-value parity vs HF torch."""

import jax.numpy as jnp
import numpy as np
import pytest

from fengshen_tpu.models.deberta_v2 import DebertaV2Config, DebertaV2Model
from fengshen_tpu.models.deberta_v2.convert import torch_to_params


def _pair(conv=0):
    torch = pytest.importorskip("torch")
    import transformers
    hf_cfg = transformers.DebertaV2Config(
        vocab_size=128, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, relative_attention=True,
        position_buckets=8, norm_rel_ebd="layer_norm", share_att_key=True,
        pos_att_type=["p2c", "c2p"], position_biased_input=False,
        conv_kernel_size=conv, attn_implementation="eager")
    torch.manual_seed(0)
    tm = transformers.DebertaV2Model(hf_cfg).eval()
    cfg = DebertaV2Config(
        vocab_size=128, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, position_buckets=8,
        conv_kernel_size=conv, dtype="float32")
    sd = {f"deberta.{k}": v for k, v in tm.state_dict().items()}
    return torch_to_params(sd, cfg)["deberta"], tm, cfg


def _compare(params, tm, cfg, atol=3e-3):
    import torch
    ids = np.array([[3, 17, 9, 42, 7, 99, 1, 5]], dtype=np.int32)
    mask = np.array([[1, 1, 1, 1, 1, 1, 1, 0]], dtype=np.int32)
    hidden = DebertaV2Model(cfg).apply(
        {"params": params}, jnp.asarray(ids),
        attention_mask=jnp.asarray(mask))
    with torch.no_grad():
        ref = tm(torch.tensor(ids, dtype=torch.long),
                 attention_mask=torch.tensor(mask, dtype=torch.long)
                 ).last_hidden_state.numpy()
    # padded positions carry no meaning; compare valid tokens only
    np.testing.assert_allclose(np.asarray(hidden)[:, :7], ref[:, :7],
                               atol=atol)


def test_deberta_forward_parity():
    params, tm, cfg = _pair(conv=0)
    _compare(params, tm, cfg)


def test_deberta_forward_parity_with_conv():
    params, tm, cfg = _pair(conv=3)
    _compare(params, tm, cfg)
