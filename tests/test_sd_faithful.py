"""Forward parity of the diffusers-faithful SD towers (VERDICT r4
missing #1 / weak #2).

No diffusers package exists in this env, so the torch oracle below is a
compact restatement of the diffusers modules themselves — built with
torch layers named exactly like diffusers' (`down_blocks.0.resnets.0…`),
so its `state_dict()` IS a diffusers-format checkpoint. The flax towers
must import that state dict via `convert.unet_to_params` /
`vae_to_params` and reproduce the oracle's outputs.

Oracle equations follow diffusers' UNet2DConditionModel /
AutoencoderKL for the SD-1.x configuration (use_linear_projection=False,
GEGLU feed-forward, conv proj_in/out; reference workload:
fengshen/examples/finetune_taiyi_stable_diffusion/finetune.py:81-144).
"""

import math

import numpy as np
import pytest

import jax
import jax.numpy as jnp

torch = pytest.importorskip("torch")
import torch.nn as tnn  # noqa: E402
import torch.nn.functional as F  # noqa: E402


# -- torch oracle (diffusers restatement) ---------------------------------

class OResnet(tnn.Module):
    def __init__(self, cin, cout, groups, eps, temb_dim=None):
        super().__init__()
        self.norm1 = tnn.GroupNorm(groups, cin, eps=eps)
        self.conv1 = tnn.Conv2d(cin, cout, 3, padding=1)
        if temb_dim:
            self.time_emb_proj = tnn.Linear(temb_dim, cout)
        self.norm2 = tnn.GroupNorm(groups, cout, eps=eps)
        self.conv2 = tnn.Conv2d(cout, cout, 3, padding=1)
        if cin != cout:
            self.conv_shortcut = tnn.Conv2d(cin, cout, 1)

    def forward(self, x, temb=None):
        h = self.conv1(F.silu(self.norm1(x)))
        if temb is not None:
            h = h + self.time_emb_proj(F.silu(temb))[:, :, None, None]
        h = self.conv2(F.silu(self.norm2(h)))
        if hasattr(self, "conv_shortcut"):
            x = self.conv_shortcut(x)
        return x + h


class OAttention(tnn.Module):
    def __init__(self, dim, heads, ctx_dim=None, qkv_bias=False):
        super().__init__()
        ctx_dim = ctx_dim or dim
        self.heads = heads
        self.to_q = tnn.Linear(dim, dim, bias=qkv_bias)
        self.to_k = tnn.Linear(ctx_dim, dim, bias=qkv_bias)
        self.to_v = tnn.Linear(ctx_dim, dim, bias=qkv_bias)
        self.to_out = tnn.ModuleList([tnn.Linear(dim, dim)])

    def forward(self, x, ctx=None):
        ctx = x if ctx is None else ctx
        b, n, c = x.shape
        hd = c // self.heads
        q = self.to_q(x).view(b, -1, self.heads, hd).transpose(1, 2)
        k = self.to_k(ctx).view(b, -1, self.heads, hd).transpose(1, 2)
        v = self.to_v(ctx).view(b, -1, self.heads, hd).transpose(1, 2)
        att = (q @ k.transpose(-1, -2)) / math.sqrt(hd)
        out = att.softmax(-1) @ v
        return self.to_out[0](
            out.transpose(1, 2).reshape(b, n, c))


class OGEGLU(tnn.Module):
    def __init__(self, dim, inner):
        super().__init__()
        self.proj = tnn.Linear(dim, 2 * inner)

    def forward(self, x):
        h, gate = self.proj(x).chunk(2, dim=-1)
        return h * F.gelu(gate)


class OFeedForward(tnn.Module):
    def __init__(self, dim):
        super().__init__()
        self.net = tnn.ModuleList(
            [OGEGLU(dim, 4 * dim), tnn.Identity(),
             tnn.Linear(4 * dim, dim)])

    def forward(self, x):
        return self.net[2](self.net[0](x))


class OTransformerBlock(tnn.Module):
    def __init__(self, dim, heads, ctx_dim):
        super().__init__()
        self.norm1 = tnn.LayerNorm(dim)
        self.attn1 = OAttention(dim, heads)
        self.norm2 = tnn.LayerNorm(dim)
        self.attn2 = OAttention(dim, heads, ctx_dim)
        self.norm3 = tnn.LayerNorm(dim)
        self.ff = OFeedForward(dim)

    def forward(self, x, ctx):
        x = x + self.attn1(self.norm1(x))
        x = x + self.attn2(self.norm2(x), ctx)
        return x + self.ff(self.norm3(x))


class OTransformer2D(tnn.Module):
    def __init__(self, dim, heads, ctx_dim, groups):
        super().__init__()
        self.norm = tnn.GroupNorm(groups, dim, eps=1e-6)
        self.proj_in = tnn.Conv2d(dim, dim, 1)
        self.transformer_blocks = tnn.ModuleList(
            [OTransformerBlock(dim, heads, ctx_dim)])
        self.proj_out = tnn.Conv2d(dim, dim, 1)

    def forward(self, x, ctx):
        b, c, h, w = x.shape
        res = x
        y = self.proj_in(self.norm(x))
        y = y.permute(0, 2, 3, 1).reshape(b, h * w, c)
        y = self.transformer_blocks[0](y, ctx)
        y = y.reshape(b, h, w, c).permute(0, 3, 1, 2)
        return self.proj_out(y) + res


class ODownsample(tnn.Module):
    def __init__(self, ch, vae=False):
        super().__init__()
        self.vae = vae
        self.conv = tnn.Conv2d(ch, ch, 3, stride=2,
                               padding=0 if vae else 1)

    def forward(self, x):
        if self.vae:
            x = F.pad(x, (0, 1, 0, 1))
        return self.conv(x)


class OUpsample(tnn.Module):
    def __init__(self, ch):
        super().__init__()
        self.conv = tnn.Conv2d(ch, ch, 3, padding=1)

    def forward(self, x):
        return self.conv(F.interpolate(x, scale_factor=2.0,
                                       mode="nearest"))


class OUNet(tnn.Module):
    """diffusers UNet2DConditionModel restated, small config:
    blocks (32, 64), layers_per_block=1, heads 2, ctx 32, groups 8."""

    CH = (32, 64)
    GROUPS = 8
    HEADS = 2
    CTX = 32
    LAYERS = 1
    EPS = 1e-5

    def __init__(self):
        super().__init__()
        ch0, ch1 = self.CH
        tdim = ch0 * 4

        class TE(tnn.Module):
            def __init__(self):
                super().__init__()
                self.linear_1 = tnn.Linear(ch0, tdim)
                self.linear_2 = tnn.Linear(tdim, tdim)

            def forward(self, t):
                return self.linear_2(F.silu(self.linear_1(t)))

        self.time_embedding = TE()
        self.conv_in = tnn.Conv2d(4, ch0, 3, padding=1)

        db0 = tnn.Module()
        db0.resnets = tnn.ModuleList(
            [OResnet(ch0, ch0, self.GROUPS, self.EPS, tdim)])
        db0.attentions = tnn.ModuleList(
            [OTransformer2D(ch0, self.HEADS, self.CTX, self.GROUPS)])
        db0.downsamplers = tnn.ModuleList([ODownsample(ch0)])
        db1 = tnn.Module()
        db1.resnets = tnn.ModuleList(
            [OResnet(ch0, ch1, self.GROUPS, self.EPS, tdim)])
        self.down_blocks = tnn.ModuleList([db0, db1])

        mid = tnn.Module()
        mid.resnets = tnn.ModuleList(
            [OResnet(ch1, ch1, self.GROUPS, self.EPS, tdim),
             OResnet(ch1, ch1, self.GROUPS, self.EPS, tdim)])
        mid.attentions = tnn.ModuleList(
            [OTransformer2D(ch1, self.HEADS, self.CTX, self.GROUPS)])
        self.mid_block = mid

        ub0 = tnn.Module()  # UpBlock2D at ch1
        ub0.resnets = tnn.ModuleList(
            [OResnet(ch1 + ch1, ch1, self.GROUPS, self.EPS, tdim),
             OResnet(ch1 + ch0, ch1, self.GROUPS, self.EPS, tdim)])
        ub0.upsamplers = tnn.ModuleList([OUpsample(ch1)])
        ub1 = tnn.Module()  # CrossAttnUpBlock2D at ch0
        ub1.resnets = tnn.ModuleList(
            [OResnet(ch1 + ch0, ch0, self.GROUPS, self.EPS, tdim),
             OResnet(ch0 + ch0, ch0, self.GROUPS, self.EPS, tdim)])
        ub1.attentions = tnn.ModuleList(
            [OTransformer2D(ch0, self.HEADS, self.CTX, self.GROUPS),
             OTransformer2D(ch0, self.HEADS, self.CTX, self.GROUPS)])
        self.up_blocks = tnn.ModuleList([ub0, ub1])

        self.conv_norm_out = tnn.GroupNorm(self.GROUPS, ch0, eps=self.EPS)
        self.conv_out = tnn.Conv2d(ch0, 4, 3, padding=1)

    def timestep_embedding(self, t):
        half = self.CH[0] // 2
        exponent = -math.log(10000.0) * torch.arange(half).float() / half
        emb = t.float()[:, None] * exponent.exp()[None]
        emb = torch.cat([emb.sin(), emb.cos()], dim=-1)
        return torch.cat([emb[:, half:], emb[:, :half]], dim=-1)

    def forward(self, latents, t, ctx):
        temb = self.time_embedding(self.timestep_embedding(t))
        h = self.conv_in(latents)
        skips = [h]
        d0 = self.down_blocks[0]
        h = d0.resnets[0](h, temb)
        h = d0.attentions[0](h, ctx)
        skips.append(h)
        h = d0.downsamplers[0](h)
        skips.append(h)
        d1 = self.down_blocks[1]
        h = d1.resnets[0](h, temb)
        skips.append(h)

        h = self.mid_block.resnets[0](h, temb)
        h = self.mid_block.attentions[0](h, ctx)
        h = self.mid_block.resnets[1](h, temb)

        u0 = self.up_blocks[0]
        for j in range(2):
            h = torch.cat([h, skips.pop()], dim=1)
            h = u0.resnets[j](h, temb)
        h = u0.upsamplers[0](h)
        u1 = self.up_blocks[1]
        for j in range(2):
            h = torch.cat([h, skips.pop()], dim=1)
            h = u1.resnets[j](h, temb)
            h = u1.attentions[j](h, ctx)

        return self.conv_out(F.silu(self.conv_norm_out(h)))


class OVAEAttn(tnn.Module):
    def __init__(self, ch, groups):
        super().__init__()
        self.group_norm = tnn.GroupNorm(groups, ch, eps=1e-6)
        self.to_q = tnn.Linear(ch, ch)
        self.to_k = tnn.Linear(ch, ch)
        self.to_v = tnn.Linear(ch, ch)
        self.to_out = tnn.ModuleList([tnn.Linear(ch, ch)])

    def forward(self, x):
        b, c, h, w = x.shape
        y = self.group_norm(x)
        y = y.permute(0, 2, 3, 1).reshape(b, h * w, c)
        q, k, v = self.to_q(y), self.to_k(y), self.to_v(y)
        att = (q @ k.transpose(-1, -2)) / math.sqrt(c)
        y = self.to_out[0](att.softmax(-1) @ v)
        return x + y.reshape(b, h, w, c).permute(0, 3, 1, 2)


class OVAE(tnn.Module):
    """diffusers AutoencoderKL restated; blocks (16, 32),
    layers_per_block=1, groups 4."""

    CH = (16, 32)
    GROUPS = 4

    def __init__(self):
        super().__init__()
        ch0, ch1 = self.CH

        enc = tnn.Module()
        enc.conv_in = tnn.Conv2d(3, ch0, 3, padding=1)
        e0 = tnn.Module()
        e0.resnets = tnn.ModuleList(
            [OResnet(ch0, ch0, self.GROUPS, 1e-6)])
        e0.downsamplers = tnn.ModuleList([ODownsample(ch0, vae=True)])
        e1 = tnn.Module()
        e1.resnets = tnn.ModuleList(
            [OResnet(ch0, ch1, self.GROUPS, 1e-6)])
        enc.down_blocks = tnn.ModuleList([e0, e1])
        mid = tnn.Module()
        mid.resnets = tnn.ModuleList(
            [OResnet(ch1, ch1, self.GROUPS, 1e-6),
             OResnet(ch1, ch1, self.GROUPS, 1e-6)])
        mid.attentions = tnn.ModuleList([OVAEAttn(ch1, self.GROUPS)])
        enc.mid_block = mid
        enc.conv_norm_out = tnn.GroupNorm(self.GROUPS, ch1, eps=1e-6)
        enc.conv_out = tnn.Conv2d(ch1, 8, 3, padding=1)
        self.encoder = enc

        dec = tnn.Module()
        dec.conv_in = tnn.Conv2d(4, ch1, 3, padding=1)
        dmid = tnn.Module()
        dmid.resnets = tnn.ModuleList(
            [OResnet(ch1, ch1, self.GROUPS, 1e-6),
             OResnet(ch1, ch1, self.GROUPS, 1e-6)])
        dmid.attentions = tnn.ModuleList([OVAEAttn(ch1, self.GROUPS)])
        dec.mid_block = dmid
        d0 = tnn.Module()
        d0.resnets = tnn.ModuleList(
            [OResnet(ch1, ch1, self.GROUPS, 1e-6),
             OResnet(ch1, ch1, self.GROUPS, 1e-6)])
        d0.upsamplers = tnn.ModuleList([OUpsample(ch1)])
        d1 = tnn.Module()
        d1.resnets = tnn.ModuleList(
            [OResnet(ch1, ch0, self.GROUPS, 1e-6),
             OResnet(ch0, ch0, self.GROUPS, 1e-6)])
        dec.up_blocks = tnn.ModuleList([d0, d1])
        dec.conv_norm_out = tnn.GroupNorm(self.GROUPS, ch0, eps=1e-6)
        dec.conv_out = tnn.Conv2d(ch0, 3, 3, padding=1)
        self.decoder = dec

        self.quant_conv = tnn.Conv2d(8, 8, 1)
        self.post_quant_conv = tnn.Conv2d(4, 4, 1)

    def encode(self, x):
        e = self.encoder
        h = e.conv_in(x)
        h = e.down_blocks[0].resnets[0](h)
        h = e.down_blocks[0].downsamplers[0](h)
        h = e.down_blocks[1].resnets[0](h)
        h = e.mid_block.resnets[0](h)
        h = e.mid_block.attentions[0](h)
        h = e.mid_block.resnets[1](h)
        h = e.conv_out(F.silu(e.conv_norm_out(h)))
        moments = self.quant_conv(h)
        mean, logvar = moments.chunk(2, dim=1)
        return mean, logvar.clamp(-30.0, 20.0)

    def decode(self, z):
        d = self.decoder
        h = d.conv_in(self.post_quant_conv(z))
        h = d.mid_block.resnets[0](h)
        h = d.mid_block.attentions[0](h)
        h = d.mid_block.resnets[1](h)
        for i in range(2):
            blk = d.up_blocks[i]
            for r in blk.resnets:
                h = r(h)
            if i == 0:
                h = blk.upsamplers[0](h)
        return d.conv_out(F.silu(d.conv_norm_out(h)))


# -- tests ----------------------------------------------------------------

def _nhwc(x):
    return jnp.asarray(x.detach().numpy().transpose(0, 2, 3, 1))


def test_sd_unet_forward_parity():
    from fengshen_tpu.models.stable_diffusion.convert import unet_to_params
    from fengshen_tpu.models.stable_diffusion.unet_sd import (
        SDUNetConfig, SDUNet2DConditionModel)

    torch.manual_seed(0)
    oracle = OUNet().eval()
    cfg = SDUNetConfig.small_test_config()
    params = unet_to_params(oracle.state_dict())
    model = SDUNet2DConditionModel(cfg)

    rng = np.random.RandomState(1)
    lat = torch.tensor(rng.randn(2, 4, 8, 8), dtype=torch.float32)
    t = torch.tensor([7, 421])
    ctx = torch.tensor(rng.randn(2, 5, 32), dtype=torch.float32)
    with torch.no_grad():
        ref = oracle(lat, t, ctx)
    ours = model.apply({"params": params}, _nhwc(lat),
                       jnp.asarray(t.numpy()), jnp.asarray(ctx.numpy()))
    np.testing.assert_allclose(np.asarray(ours),
                               ref.numpy().transpose(0, 2, 3, 1),
                               atol=2e-4)
    # the import covered every oracle parameter (no silently-missed keys)
    n_oracle = len(oracle.state_dict())
    n_flax = len(jax.tree_util.tree_leaves(params))
    assert n_oracle == n_flax, (n_oracle, n_flax)


def test_sd_vae_forward_parity():
    from fengshen_tpu.models.stable_diffusion.convert import vae_to_params
    from fengshen_tpu.models.stable_diffusion.vae_sd import (
        SDVAEConfig, SDAutoencoderKL)

    torch.manual_seed(0)
    oracle = OVAE().eval()
    cfg = SDVAEConfig.small_test_config()
    params = vae_to_params(oracle.state_dict())
    model = SDAutoencoderKL(cfg)

    rng = np.random.RandomState(2)
    px = torch.tensor(rng.randn(1, 3, 16, 16), dtype=torch.float32)
    with torch.no_grad():
        mean_ref, logvar_ref = oracle.encode(px)
        recon_ref = oracle.decode(mean_ref)
    mean, logvar = model.apply({"params": params}, _nhwc(px),
                               method=SDAutoencoderKL.encode)
    np.testing.assert_allclose(np.asarray(mean),
                               mean_ref.numpy().transpose(0, 2, 3, 1),
                               atol=2e-4)
    np.testing.assert_allclose(np.asarray(logvar),
                               logvar_ref.numpy().transpose(0, 2, 3, 1),
                               atol=2e-4)
    recon = model.apply({"params": params}, mean,
                        method=SDAutoencoderKL.decode)
    np.testing.assert_allclose(np.asarray(recon),
                               recon_ref.numpy().transpose(0, 2, 3, 1),
                               atol=5e-4)


def test_sd_vae_old_attention_naming():
    """2022-era diffusers VAE checkpoints use query/key/value/proj_attn —
    the importer must accept both namings."""
    from fengshen_tpu.models.stable_diffusion.convert import vae_to_params

    torch.manual_seed(0)
    oracle = OVAE().eval()
    state = dict(oracle.state_dict())
    renames = {"to_q": "query", "to_k": "key", "to_v": "value",
               "to_out.0": "proj_attn"}
    old_state = {}
    for k, v in state.items():
        for new, old in renames.items():
            if f"attentions.0.{new}." in k:
                k = k.replace(f"attentions.0.{new}.",
                              f"attentions.0.{old}.")
                break
        old_state[k] = v
    assert any("proj_attn" in k for k in old_state)
    a = vae_to_params(state)
    b = vae_to_params(old_state)
    for pa, pb in zip(jax.tree_util.tree_flatten_with_path(a)[0],
                      jax.tree_util.tree_flatten_with_path(b)[0]):
        assert pa[0] == pb[0]
        np.testing.assert_array_equal(pa[1], pb[1])


def test_sd_unet_export_round_trip():
    """fs→diffusers export (derived inverse) is bit-exact."""
    from fengshen_tpu.models.stable_diffusion.convert import (
        unet_params_to_diffusers, unet_to_params)

    torch.manual_seed(0)
    oracle = OUNet()
    state = oracle.state_dict()
    params = unet_to_params(state)
    out = unet_params_to_diffusers(params, state)
    for k, v in state.items():
        np.testing.assert_array_equal(out[k], v.numpy(), err_msg=k)


def test_sd_config_from_diffusers_json():
    from fengshen_tpu.models.stable_diffusion.convert import (
        sd_unet_config_from_diffusers, sd_vae_config_from_diffusers)

    unet_cfg = sd_unet_config_from_diffusers({
        "_class_name": "UNet2DConditionModel", "sample_size": 64,
        "in_channels": 4, "out_channels": 4,
        "block_out_channels": [320, 640, 1280, 1280],
        "layers_per_block": 2, "cross_attention_dim": 768,
        "attention_head_dim": 8, "norm_num_groups": 32,
        "down_block_types": ["CrossAttnDownBlock2D"] * 3 + [
            "DownBlock2D"],
        "up_block_types": ["UpBlock2D"] + ["CrossAttnUpBlock2D"] * 3,
        "act_fn": "silu", "center_input_sample": False})
    assert unet_cfg.block_out_channels == (320, 640, 1280, 1280)
    assert unet_cfg.attention_head_dim == 8
    vae_cfg = sd_vae_config_from_diffusers({
        "_class_name": "AutoencoderKL", "latent_channels": 4,
        "block_out_channels": [128, 256, 512, 512],
        "layers_per_block": 2, "norm_num_groups": 32, "act_fn": "silu"})
    assert vae_cfg.block_out_channels == (128, 256, 512, 512)


@pytest.mark.slow
def test_finetune_over_faithful_towers_e2e(tmp_path, mesh8):
    """The Taiyi-SD finetune driver runs over the faithful towers with
    weights imported from a (synthetic) released diffusers pipeline dir
    — the full reference workload path (finetune.py:81-144)."""
    import csv
    import json as json_mod
    import os

    pytest.importorskip("PIL")
    from PIL import Image
    from transformers import BertTokenizer

    from fengshen_tpu.examples.finetune_taiyi_stable_diffusion import (
        finetune)
    from fengshen_tpu.models.bert import BertConfig

    # text tower dir
    chars = list("一张测试图片的照狗")
    vocab = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"] + \
        sorted(set(chars))
    (tmp_path / "vocab.txt").write_text("\n".join(vocab))
    tok = BertTokenizer(str(tmp_path / "vocab.txt"))
    model_dir = tmp_path / "model"
    model_dir.mkdir()
    tok.save_pretrained(str(model_dir))
    BertConfig.small_test_config(vocab_size=len(tok)).save_pretrained(
        str(model_dir))

    # synthetic "released" diffusers pipeline dir with oracle weights
    pipe = tmp_path / "pipeline"
    torch.manual_seed(0)
    for sub, oracle, cfg in (
            ("unet", OUNet(), {
                "sample_size": 4, "in_channels": 4, "out_channels": 4,
                "block_out_channels": [32, 64], "layers_per_block": 1,
                "cross_attention_dim": 32, "attention_head_dim": 2,
                "norm_num_groups": 8,
                "down_block_types": ["CrossAttnDownBlock2D",
                                     "DownBlock2D"],
                "up_block_types": ["UpBlock2D", "CrossAttnUpBlock2D"]}),
            ("vae", OVAE(), {
                "in_channels": 3, "out_channels": 3,
                "latent_channels": 4, "block_out_channels": [16, 32],
                "layers_per_block": 1, "norm_num_groups": 4})):
        os.makedirs(pipe / sub)
        with open(pipe / sub / "config.json", "w") as f:
            json_mod.dump(cfg, f)
        torch.save(oracle.state_dict(),
                   pipe / sub / "diffusion_pytorch_model.bin")

    # tiny image/caption dataset
    img_dir = tmp_path / "imgs"
    img_dir.mkdir()
    rng = np.random.RandomState(0)
    rows = []
    for i in range(4):
        arr = (rng.rand(32, 32, 3) * 255).astype(np.uint8)
        p = img_dir / f"i{i}.png"
        Image.fromarray(arr).save(p)
        rows.append({"image": str(p), "caption": "一张测试图片"})
    csv_path = tmp_path / "data.csv"
    with open(csv_path, "w", newline="") as f:
        w = csv.DictWriter(f, fieldnames=["image", "caption"])
        w.writeheader()
        w.writerows(rows)

    finetune.main([
        "--model_path", str(model_dir),
        "--sd_pipeline_path", str(pipe),
        "--train_csv", str(csv_path),
        "--train_batchsize", "2", "--max_steps", "2",
        "--log_every_n_steps", "1", "--warmup_steps", "1",
        "--default_root_dir", str(tmp_path / "runs"),
        "--save_ckpt_path", str(tmp_path / "ckpt"),
        "--load_ckpt_path", str(tmp_path / "ckpt"),
        "--image_size", "32", "--max_length", "16", "--seed", "1"])
    lines = [json_mod.loads(l)
             for l in open(tmp_path / "runs" / "metrics.jsonl")]
    losses = [l["loss"] for l in lines if "loss" in l]
    assert len(losses) == 2 and all(np.isfinite(losses))


def test_sd_unet_sharded_matches_replicated(mesh8):
    """SD_PARTITION_RULES shard the faithful UNet over fsdp+tensor
    without changing the math (the 860M Taiyi-SD finetune must shard on
    a pod, not replicate).

    Formerly a non-strict xfail (seed NOTES.md item 3): the divergence
    was GSPMD back-propagating downstream weight shards onto the
    timestep sin|cos concat / up-block skip concats, whose dims then
    became sharded matmul contractions — mispartitioned on this XLA
    build. Fixed by the `with_logical_constraint` replication pins in
    unet_sd.py (docs/sharding.md "Root cause"); parity is now a hard
    tight-tolerance assertion."""
    from fengshen_tpu.models.stable_diffusion.unet_sd import (
        SDUNetConfig, SDUNet2DConditionModel)
    from fengshen_tpu.parallel import make_shardings
    from fengshen_tpu.parallel.partition import match_partition_rules

    # channels divisible by fsdp=2/tensor=2 so the rules really engage
    cfg = SDUNetConfig.small_test_config(
        block_out_channels=(32, 64), cross_attention_dim=32)
    model = SDUNet2DConditionModel(cfg)
    rng = np.random.RandomState(9)
    lat = jnp.asarray(rng.randn(2, 8, 8, 4), jnp.float32)
    t = jnp.asarray([3, 411])
    ctx = jnp.asarray(rng.randn(2, 5, 32), jnp.float32)
    params = model.init(jax.random.PRNGKey(0), lat, t, ctx)["params"]
    ref = model.apply({"params": params}, lat, t, ctx)

    specs = match_partition_rules(model.partition_rules(), params)
    shardings = make_shardings(specs, params, mesh8)
    sharded = jax.device_put(params, shardings)
    # the cross-attention kernels must actually be partitioned
    qk = sharded["down_blocks_0"]["attentions_0"][
        "transformer_blocks_0"]["attn2"]["to_q"]["kernel"]
    assert any(e is not None for e in qk.sharding.spec)
    out = jax.jit(lambda p: model.apply({"params": p}, lat, t, ctx))(
        sharded)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-4)
