"""End-to-end example-workload tests (tiny data, CPU mesh) — the analog of
the reference's small-data example smoke paths (SURVEY.md §4)."""

import argparse
import json
import os



import numpy as np
import pytest

pytestmark = pytest.mark.slow  # full-fit/e2e lane: run with -m slow or no -m filter



def _bert_tokenizer(tmp_path):
    from transformers import BertTokenizer
    chars = list("今天天气很好我们去公园吧然后回家机器学习模型训练数据中文测试句子")
    vocab = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"] + \
        sorted(set(chars))
    vf = tmp_path / "vocab.txt"
    vf.write_text("\n".join(vocab))
    return BertTokenizer(str(vf))


def test_erlangshen_collator(tmp_path):
    from fengshen_tpu.examples.pretrain_erlangshen_bert.pretrain_erlangshen \
        import ErLangShenCollator
    tok = _bert_tokenizer(tmp_path)
    coll = ErLangShenCollator(tok, max_seq_length=32, masked_lm_prob=0.2)
    batch = coll([{"text": "今天天气很好。我们去公园吧！然后回家。"},
                  {"text": "机器学习模型训练。中文测试句子！"}])
    assert batch["input_ids"].shape == (2, 32)
    assert batch["labels"].shape == (2, 32)
    assert batch["next_sentence_label"].shape == (2,)
    # masked positions carry original ids as labels; others -100
    lab = batch["labels"]
    assert (lab != -100).sum() > 0
    # CLS at position 0, never masked
    assert (batch["input_ids"][:, 0] == tok.cls_token_id).all()
    assert (lab[:, 0] == -100).all()


def test_erlangshen_pretrain_e2e(tmp_path, mesh8):
    """Tiny pretrain run through main() — the full example CLI surface."""
    from fengshen_tpu.examples.pretrain_erlangshen_bert import (
        pretrain_erlangshen)
    from fengshen_tpu.models.megatron_bert import MegatronBertConfig

    tok = _bert_tokenizer(tmp_path)
    model_dir = tmp_path / "model"
    model_dir.mkdir()
    tok.save_pretrained(str(model_dir))
    MegatronBertConfig(
        vocab_size=len(tok), hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, dtype="float32").save_pretrained(
            str(model_dir))

    train = tmp_path / "train.json"
    with open(train, "w") as f:
        for i in range(16):
            f.write(json.dumps({"text": "今天天气很好。我们去公园吧！"},
                               ensure_ascii=False) + "\n")

    pretrain_erlangshen.main([
        "--model_path", str(model_dir), "--train_file", str(train),
        "--train_batchsize", "4", "--max_steps", "2", "--max_seq_length",
        "32", "--log_every_n_steps", "1", "--warmup_steps", "1",
        "--default_root_dir", str(tmp_path / "runs"),
        "--save_ckpt_path", str(tmp_path / "ckpt"),
        "--load_ckpt_path", str(tmp_path / "ckpt"),
        "--every_n_train_steps", "2", "--seed", "1"])

    lines = [json.loads(l) for l in
             open(tmp_path / "runs" / "metrics.jsonl")]
    losses = [l["loss"] for l in lines if "loss" in l]
    assert len(losses) == 2 and all(np.isfinite(losses))
    # checkpoint written
    assert os.path.isdir(tmp_path / "ckpt")


def test_llama_sft_collator():
    from transformers import AutoTokenizer
    from fengshen_tpu.examples.ziya_llama.finetune_ziya_llama import (
        LlamaSFTCollator)

    class FakeTok:
        pad_token_id = 0
        eos_token_id = 2

        def encode(self, text, add_special_tokens=True):
            ids = [min(3 + (ord(c) % 90), 95) for c in text]
            return ([1] + ids) if add_special_tokens else ids

    coll = LlamaSFTCollator(FakeTok(), max_seq_length=32)
    batch = coll([{"query": "你好", "answer": "hello"}])
    assert batch["input_ids"].shape == (1, 32)
    labels = batch["labels"][0]
    n_prompt = len(FakeTok().encode("<human>:你好\n<bot>:"))
    assert (labels[:n_prompt] == -100).all()
    assert (labels[n_prompt] != -100)
    # answer ends with eos label
    valid = labels[labels != -100]
    assert valid[-1] == 2


def test_ziya_sft_north_star_tp_flash_e2e(tmp_path, mesh8):
    """The north-star path (SURVEY §3.1): Ziya SFT main() end-to-end with
    tensor parallelism + flash attention + PADDED batches (segment ids keep
    the fused path) on the virtual mesh, then the TP generation predict
    path on the trained module."""
    from fengshen_tpu.examples.ziya_llama import finetune_ziya_llama
    from fengshen_tpu.models.llama import LlamaConfig

    model_dir = tmp_path / "model"
    model_dir.mkdir()

    class CharTok:
        pad_token_id = 0
        eos_token_id = 2

        def encode(self, text, add_special_tokens=True):
            ids = [min(3 + (ord(c) % 90), 95) for c in text]
            return ([1] + ids) if add_special_tokens else ids

        @classmethod
        def from_pretrained(cls, path):
            return cls()

    cfg = LlamaConfig(vocab_size=128, hidden_size=32, intermediate_size=64,
                      num_hidden_layers=2, num_attention_heads=4,
                      max_position_embeddings=64, dtype="float32",
                      attention_impl="flash")
    cfg.save_pretrained(str(model_dir))

    train = tmp_path / "sft.json"
    with open(train, "w") as f:
        for i in range(16):
            f.write(json.dumps({"query": "你好" * (1 + i % 3),
                                "answer": "hello"},
                               ensure_ascii=False) + "\n")

    import unittest.mock as mock
    with mock.patch("transformers.AutoTokenizer.from_pretrained",
                    CharTok.from_pretrained):
        finetune_ziya_llama.main([
            "--model_path", str(model_dir), "--train_file", str(train),
            "--train_batchsize", "4", "--max_steps", "2",
            "--max_seq_length", "32", "--log_every_n_steps", "1",
            "--warmup_steps", "1",
            "--default_root_dir", str(tmp_path / "runs"),
            "--save_ckpt_path", str(tmp_path / "ckpt"),
            "--load_ckpt_path", str(tmp_path / "ckpt"),
            "--tensor_model_parallel_size", "2",
            "--fsdp_parallel_size", "2",
            "--data_parallel_size", "2", "--seed", "1"])

    lines = [json.loads(l) for l in
             open(tmp_path / "runs" / "metrics.jsonl")]
    losses = [l["loss"] for l in lines if "loss" in l]
    assert len(losses) == 2 and all(np.isfinite(losses))
    # MFU instrumentation present on the logged steps (CPU has no peak
    # table entry, so just assert tokens/sec is measured)
    assert all(l["tokens_per_sec"] > 0 for l in lines if "loss" in l)

    # generation predict path (SURVEY §3.1 predict flow) on the saved ckpt
    import argparse

    from fengshen_tpu.data import UniversalDataModule
    from fengshen_tpu.examples.ziya_llama.finetune_ziya_llama import Llama
    from fengshen_tpu.models.model_utils import add_module_args
    from fengshen_tpu.trainer import Trainer, add_trainer_args
    from fengshen_tpu.utils import UniversalCheckpoint

    parser = argparse.ArgumentParser()
    add_module_args(parser)
    add_trainer_args(parser)
    UniversalDataModule.add_data_specific_args(parser)
    UniversalCheckpoint.add_argparse_args(parser)
    Llama.add_module_specific_args(parser)
    args = parser.parse_args([
        "--model_path", str(model_dir), "--max_seq_length", "32",
        "--default_root_dir", str(tmp_path / "runs"),
        "--load_ckpt_path", str(tmp_path / "ckpt"),
        "--tensor_model_parallel_size", "2",
        "--fsdp_parallel_size", "2", "--data_parallel_size", "2"])
    trainer = Trainer(args)
    module = Llama(args, cfg)
    import jax as _jax
    params = module.init_params(_jax.random.PRNGKey(0))
    tok = CharTok()
    prompt = np.asarray([tok.encode("<human>:你好\n<bot>:")], np.int32)
    outs = trainer.predict(module, [{"input_ids": prompt}],
                           params=params, max_new_tokens=4)
    assert outs[0].shape == (1, prompt.shape[1] + 4)


def test_ziya_sft_packed_e2e(tmp_path, mesh8):
    """--packed: sequence-packed SFT fit end-to-end on the mesh (the
    packed collator + segment-id attention + restarting position ids)."""
    from fengshen_tpu.examples.ziya_llama import finetune_ziya_llama
    from fengshen_tpu.models.llama import LlamaConfig

    model_dir = tmp_path / "model"
    model_dir.mkdir()

    class CharTok:
        pad_token_id = 0
        eos_token_id = 2

        def encode(self, text, add_special_tokens=True):
            ids = [min(3 + (ord(c) % 90), 95) for c in text]
            return ([1] + ids) if add_special_tokens else ids

        @classmethod
        def from_pretrained(cls, path):
            return cls()

    cfg = LlamaConfig(vocab_size=128, hidden_size=32, intermediate_size=64,
                      num_hidden_layers=2, num_attention_heads=4,
                      max_position_embeddings=64, dtype="float32",
                      attention_impl="flash")
    cfg.save_pretrained(str(model_dir))

    train = tmp_path / "sft.json"
    with open(train, "w") as f:
        for i in range(16):
            f.write(json.dumps({"query": "你好" * (1 + i % 3),
                                "answer": "hello"},
                               ensure_ascii=False) + "\n")

    import unittest.mock as mock
    with mock.patch("transformers.AutoTokenizer.from_pretrained",
                    CharTok.from_pretrained):
        finetune_ziya_llama.main([
            "--model_path", str(model_dir), "--train_file", str(train),
            "--train_batchsize", "4", "--max_steps", "2",
            "--max_seq_length", "64", "--log_every_n_steps", "1",
            "--warmup_steps", "1", "--packed",
            "--default_root_dir", str(tmp_path / "runs"),
            "--save_ckpt_path", str(tmp_path / "ckpt"),
            "--seed", "1"])

    lines = [json.loads(l) for l in
             open(tmp_path / "runs" / "metrics.jsonl")]
    losses = [l["loss"] for l in lines if "loss" in l]
    assert len(losses) == 2 and all(np.isfinite(losses))
