"""ZEN2 importer parity (VERDICT r2 item 3).

Builds a synthetic state dict with the reference naming
(fengshen/models/zen2/modeling.py) and checks our converted flax forward
against a numpy oracle restating the reference equations: t2t relative
sinusoidal basis (:367-384), AC/BD attention with the reference's
swapped r-bias roles and _shift (:440-509), ngram side stack + position
matrix fusion (:609-645), and the tied MLM head (:660-706).
"""

import numpy as np
import pytest


H, NH, HD, L, WL, V, NV, TT = 16, 2, 8, 2, 1, 50, 20, 2


def _rng_sd():
    rng = np.random.RandomState(0)

    def r(*shape):
        return rng.randn(*shape).astype(np.float32) * 0.1

    sd = {
        "bert.embeddings.word_embeddings.weight": r(V, H),
        "bert.embeddings.token_type_embeddings.weight": r(TT, H),
        "bert.embeddings.LayerNorm.weight": 1 + r(H),
        "bert.embeddings.LayerNorm.bias": r(H),
        "bert.word_embeddings.word_embeddings.weight": r(NV, H),
        "bert.word_embeddings.token_type_embeddings.weight": r(TT, H),
        "bert.word_embeddings.LayerNorm.weight": 1 + r(H),
        "bert.word_embeddings.LayerNorm.bias": r(H),
        "bert.pooler.dense.weight": r(H, H),
        "bert.pooler.dense.bias": r(H),
        "cls.predictions.transform.dense.weight": r(H, H),
        "cls.predictions.transform.dense.bias": r(H),
        "cls.predictions.transform.LayerNorm.weight": 1 + r(H),
        "cls.predictions.transform.LayerNorm.bias": r(H),
        "cls.predictions.bias": r(V),
    }

    def layer(prefix):
        sd.update({
            f"{prefix}.attention.self.query.weight": r(H, H),
            f"{prefix}.attention.self.query.bias": r(H),
            f"{prefix}.attention.self.key.weight": r(H, H),
            f"{prefix}.attention.self.key.bias": r(H),
            f"{prefix}.attention.self.value.weight": r(H, H),
            f"{prefix}.attention.self.value.bias": r(H),
            f"{prefix}.attention.self.r_r_bias": r(NH, HD),
            f"{prefix}.attention.self.r_w_bias": r(NH, HD),
            f"{prefix}.attention.output.dense.weight": r(H, H),
            f"{prefix}.attention.output.dense.bias": r(H),
            f"{prefix}.attention.output.LayerNorm.weight": 1 + r(H),
            f"{prefix}.attention.output.LayerNorm.bias": r(H),
            f"{prefix}.intermediate.dense.weight": r(2 * H, H),
            f"{prefix}.intermediate.dense.bias": r(2 * H),
            f"{prefix}.output.dense.weight": r(H, 2 * H),
            f"{prefix}.output.dense.bias": r(H),
            f"{prefix}.output.LayerNorm.weight": 1 + r(H),
            f"{prefix}.output.LayerNorm.bias": r(H),
        })

    for i in range(L):
        layer(f"bert.encoder.layer.{i}")
    for i in range(WL):
        layer(f"bert.encoder.word_layers.{i}")
    return sd


def _ln(x, w, b, eps=1e-12):
    m = x.mean(-1, keepdims=True)
    v = x.var(-1, keepdims=True)
    return (x - m) / np.sqrt(v + eps) * w + b


def _gelu(x):
    from scipy.special import erf
    return x * 0.5 * (1.0 + erf(x / np.sqrt(2.0)))


def _t2t_table(seq, dim):
    # reference get_embedding (modeling.py:367-384): [sin | cos] halves,
    # freq_i = exp(-i * log(10000)/(half-1)), offsets -seq..seq-1
    half = dim // 2
    freqs = np.exp(np.arange(half, dtype=np.float32) *
                   -(np.log(10000.0) / (half - 1)))
    offs = np.arange(-seq, seq, dtype=np.float32)
    ang = offs[:, None] * freqs[None]
    return np.concatenate([np.sin(ang), np.cos(ang)], 1)


def _rel_attention(x, sd, prefix):
    B, S, _ = x.shape

    def lin(n):
        return x @ sd[f"{prefix}.attention.self.{n}.weight"].T + \
            sd[f"{prefix}.attention.self.{n}.bias"]

    def heads(t):
        return t.reshape(B, S, NH, HD).transpose(0, 2, 1, 3)

    q, k, v = heads(lin("query")), heads(lin("key")), heads(lin("value"))
    r_r = sd[f"{prefix}.attention.self.r_r_bias"]
    r_w = sd[f"{prefix}.attention.self.r_w_bias"]
    ac = np.einsum("bnqd,bnkd->bnqk", q + r_r[None, :, None], k)
    table = _t2t_table(S, HD)                        # [2S, HD]
    b_ = np.einsum("bnqd,ld->bnql", q, table)        # [B,NH,S,2S]
    d_ = np.einsum("nd,ld->nl", r_w, table)[None, :, None]
    bd = b_ + d_
    # reference _shift: out[q, k] = in[q, k - q + S]
    shifted = np.zeros((B, NH, S, S), np.float32)
    for qi in range(S):
        for ki in range(S):
            shifted[:, :, qi, ki] = bd[:, :, qi, ki - qi + S]
    scores = (ac + shifted) / np.sqrt(HD)
    probs = np.exp(scores - scores.max(-1, keepdims=True))
    probs = probs / probs.sum(-1, keepdims=True)
    ctx = np.einsum("bnqk,bnkd->bnqd", probs, v)
    ctx = ctx.transpose(0, 2, 1, 3).reshape(B, S, H)
    out = ctx @ sd[f"{prefix}.attention.output.dense.weight"].T + \
        sd[f"{prefix}.attention.output.dense.bias"]
    h = _ln(x + out, sd[f"{prefix}.attention.output.LayerNorm.weight"],
            sd[f"{prefix}.attention.output.LayerNorm.bias"])
    mid = _gelu(h @ sd[f"{prefix}.intermediate.dense.weight"].T +
                sd[f"{prefix}.intermediate.dense.bias"])
    out = mid @ sd[f"{prefix}.output.dense.weight"].T + \
        sd[f"{prefix}.output.dense.bias"]
    return _ln(h + out, sd[f"{prefix}.output.LayerNorm.weight"],
               sd[f"{prefix}.output.LayerNorm.bias"])


def _oracle(sd, ids, ngram_ids, pos_matrix):
    emb = sd["bert.embeddings.word_embeddings.weight"][ids] + \
        sd["bert.embeddings.token_type_embeddings.weight"][0]
    hidden = _ln(emb, sd["bert.embeddings.LayerNorm.weight"],
                 sd["bert.embeddings.LayerNorm.bias"])
    ng = sd["bert.word_embeddings.word_embeddings.weight"][ngram_ids] + \
        sd["bert.word_embeddings.token_type_embeddings.weight"][0]
    ng = _ln(ng, sd["bert.word_embeddings.LayerNorm.weight"],
             sd["bert.word_embeddings.LayerNorm.bias"])
    for i in range(L):
        hidden = _rel_attention(hidden, sd, f"bert.encoder.layer.{i}")
        if i < WL:
            ng = _rel_attention(ng, sd, f"bert.encoder.word_layers.{i}")
        # reference modeling.py:636 — fusion on EVERY layer, outside the
        # word-layer gate
        hidden = hidden + np.einsum("bsm,bmh->bsh", pos_matrix, ng)
    return hidden


@pytest.fixture
def inputs():
    rng = np.random.RandomState(1)
    ids = rng.randint(0, V, (2, 6))
    ngram_ids = rng.randint(1, NV, (2, 3))
    pos = (rng.rand(2, 6, 3) < 0.4).astype(np.float32)
    pos = pos / np.maximum(pos.sum(-1, keepdims=True), 1.0)
    return ids, ngram_ids, pos


def _cfg():
    from fengshen_tpu.models.zen2 import Zen2Config
    return Zen2Config(
        vocab_size=V, hidden_size=H, num_hidden_layers=L,
        num_attention_heads=NH, intermediate_size=2 * H,
        max_position_embeddings=32, type_vocab_size=TT,
        ngram_vocab_size=NV, num_hidden_word_layers=WL, dtype="float32")


def test_zen2_convert_tower_parity(inputs):
    import jax.numpy as jnp

    from fengshen_tpu.models.zen2 import Zen2Model
    from fengshen_tpu.models.zen2.convert import torch_to_params

    ids, ngram_ids, pos = inputs
    sd = _rng_sd()
    cfg = _cfg()
    params = torch_to_params(sd, cfg, head="none")
    model = Zen2Model(cfg, add_pooling_layer=False)
    hidden, _ = model.apply({"params": params}, jnp.asarray(ids),
                            ngram_ids=jnp.asarray(ngram_ids),
                            ngram_positions=jnp.asarray(pos))
    ref = _oracle(sd, ids, ngram_ids, pos)
    np.testing.assert_allclose(np.asarray(hidden), ref, atol=3e-4)


def test_zen2_convert_mlm_parity(inputs):
    import jax.numpy as jnp

    from fengshen_tpu.models.zen2 import Zen2ForMaskedLM
    from fengshen_tpu.models.zen2.convert import torch_to_params

    ids, ngram_ids, pos = inputs
    sd = _rng_sd()
    cfg = _cfg()
    params = torch_to_params(sd, cfg, head="masked_lm")
    model = Zen2ForMaskedLM(cfg)
    logits = model.apply({"params": params}, jnp.asarray(ids),
                         ngram_ids=jnp.asarray(ngram_ids),
                         ngram_positions=jnp.asarray(pos))
    hidden = _oracle(sd, ids, ngram_ids, pos)
    h = _gelu(hidden @ sd["cls.predictions.transform.dense.weight"].T +
              sd["cls.predictions.transform.dense.bias"])
    h = _ln(h, sd["cls.predictions.transform.LayerNorm.weight"],
            sd["cls.predictions.transform.LayerNorm.bias"])
    ref = h @ sd["bert.embeddings.word_embeddings.weight"].T + \
        sd["cls.predictions.bias"]
    np.testing.assert_allclose(np.asarray(logits), ref, atol=3e-4)


def test_zen2_export_echo():
    """fs→reference export (derived inverse, incl. the intentional
    r_r/r_w bias swap): export(import(sd)) echoes every tensor."""
    from fengshen_tpu.models.zen2.convert import (params_to_torch_state,
                                                  torch_to_params)

    sd = _rng_sd()
    cfg = _cfg()
    params = torch_to_params(sd, cfg, head="masked_lm")
    out = params_to_torch_state(params, cfg, sd, head="masked_lm")
    assert set(out) == set(sd)
    for k in sd:
        np.testing.assert_array_equal(out[k], sd[k], err_msg=k)
