"""AOT compile-cache subsystem (fengshen_tpu/aot/, docs/aot_cache.md).

The load-bearing contracts:

- greedy decode through DESERIALIZED cached executables is
  TOKEN-IDENTICAL to freshly compiled ones (the PR-3 parity harness,
  re-run against a warm cache);
- the cache can never break a job: corrupt blobs, jax-version drift
  inside a blob, and store failures all fall back to a fresh compile,
  visible in `fstpu_aot_cache_errors_total`;
- warmup manifests record every compile site and replay (adopting by
  key under a matching code+env+config fingerprint, re-lowering
  otherwise);
- the LRU size cap, the CLI, the /healthz readiness gate, and the
  warmup/build-info gauges.
"""

import json
import os
import pickle
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from fengshen_tpu.aot import (AotConfig, AotSetup, CachedFunction,
                              ExecutableCache, WarmupManifest,
                              cached_compile, decode_avals,
                              encode_avals)
from fengshen_tpu.observability import MetricsRegistry
from fengshen_tpu.serving import ContinuousBatchingEngine, EngineConfig


@pytest.fixture(scope="module")
def tiny():
    from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    cfg = LlamaConfig(vocab_size=97, hidden_size=32, intermediate_size=64,
                      num_hidden_layers=2, num_attention_heads=4,
                      max_position_embeddings=64, dtype="float32")
    model = LlamaForCausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 4), jnp.int32))["params"]
    return model, params


def _prompts(lengths, seed=0):
    rng = np.random.RandomState(seed)
    return [rng.randint(3, 96, n).astype(np.int32) for n in lengths]


def _refs(model, params, prompts, max_new):
    from fengshen_tpu.utils.generate import generate
    outs = []
    for p in prompts:
        out = np.asarray(generate(model, params, jnp.asarray(p)[None],
                                  max_new_tokens=max_new))
        outs.append(out[0, len(p):].tolist())
    return outs


def _counts(registry, metric):
    m = registry.get(metric)
    if m is None:
        return {}
    return {k[0]: c.value for k, c in m.children()}


def _engine(tiny, tmp, registry=None, log=None, **aot_kw):
    model, params = tiny
    aot = AotSetup(AotConfig(cache_dir=str(tmp), **aot_kw),
                   registry=registry, log=log)
    return ContinuousBatchingEngine(
        model, params,
        EngineConfig(num_slots=2, buckets=(8, 16), max_new_tokens=10,
                     max_queue=16),
        aot=aot)


# ---- cached_compile core ------------------------------------------------

def test_cached_compile_miss_then_hit(tmp_path):
    reg = MetricsRegistry()
    cache = ExecutableCache(str(tmp_path), registry=reg)

    def f(a, b):
        return a @ b + 1.0

    avals = (jax.ShapeDtypeStruct((4, 4), jnp.float32),
             jax.ShapeDtypeStruct((4,), jnp.float32))
    exe1 = cached_compile(f, "t/f", *avals, cache=cache, registry=reg)
    assert _counts(reg, "fstpu_aot_cache_misses_total") == {"t/f": 1}
    files = [f for f in os.listdir(tmp_path) if f.endswith(".aotx")]
    assert len(files) == 1
    exe2 = cached_compile(f, "t/f", *avals, cache=cache, registry=reg)
    assert _counts(reg, "fstpu_aot_cache_hits_total") == {"t/f": 1}
    a = jnp.eye(4)
    b = jnp.arange(4.0)
    np.testing.assert_allclose(np.asarray(exe1(a, b)),
                               np.asarray(exe2(a, b)))
    np.testing.assert_allclose(np.asarray(exe2(a, b)),
                               np.asarray(b + 1.0))


def test_cache_key_changes_with_program_and_options(tmp_path):
    from fengshen_tpu.aot import cache_key

    def f(x):
        return x * 2

    def g(x):
        return x * 3

    aval = jax.ShapeDtypeStruct((4,), jnp.float32)
    low_f = jax.jit(f).lower(aval)
    low_g = jax.jit(g).lower(aval)
    assert cache_key("n", low_f) == cache_key("n", low_f)
    assert cache_key("n", low_f) != cache_key("n", low_g)
    assert cache_key("n", low_f) != cache_key("m", low_f)
    assert cache_key("n", low_f) != cache_key(
        "n", low_f, compiler_options={"xla_cpu_enable_fast_math": True})


def test_cached_function_store_failure_still_returns_result(
        tmp_path, monkeypatch):
    """A failing store (full disk, read-only dir) degrades to
    compile-every-time — counted, never raised."""
    reg = MetricsRegistry()
    cache = ExecutableCache(str(tmp_path), registry=reg)
    import fengshen_tpu.aot.cache as cache_mod

    def boom(compiled):
        raise OSError("disk full")

    monkeypatch.setattr(
        "jax.experimental.serialize_executable.serialize", boom)
    cf = CachedFunction(lambda x: x + 1, "t/s", cache=cache,
                        registry=reg)
    out = cf(jnp.arange(3.0))
    np.testing.assert_allclose(np.asarray(out), [1.0, 2.0, 3.0])
    assert _counts(reg, cache_mod.ERRORS_METRIC) == {"t/s": 1}
    assert not [f for f in os.listdir(tmp_path) if f.endswith(".aotx")]


# ---- the parity contract ------------------------------------------------

def test_engine_parity_through_deserialized_cache(tiny, tmp_path):
    """Populate the cache with one engine, then serve a FRESH engine
    entirely from deserialized executables: greedy decode must be
    token-identical to sequential generate (the acceptance bar couples
    the cold-start win to decode parity)."""
    model, params = tiny
    prompts = _prompts((5, 11, 16, 7))
    refs = _refs(model, params, prompts, 10)
    reg = MetricsRegistry()

    e1 = _engine(tiny, tmp_path, registry=reg)
    e1.warmup()
    assert e1.generate_all(prompts) == refs
    stored = [f for f in os.listdir(tmp_path) if f.endswith(".aotx")]
    assert len(stored) >= 4   # 2 prefill buckets + assign + decode

    reg2 = MetricsRegistry()
    e2 = _engine(tiny, tmp_path, registry=reg2)
    e2.warmup()
    assert e2.generate_all(prompts) == refs
    hits = _counts(reg2, "fstpu_aot_cache_hits_total")
    assert sum(hits.values()) >= 4, hits
    assert _counts(reg2, "fstpu_aot_cache_misses_total") == {}


def test_corrupt_blob_never_fails_job(tiny, tmp_path):
    """Truncate/garble every blob: the engine must warm up by
    recompiling, errors_total must show it, and parity must hold."""
    model, params = tiny
    prompts = _prompts((5, 12))
    refs = _refs(model, params, prompts, 8)
    e1 = _engine(tiny, tmp_path)
    e1.warmup()
    e1.generate_all(prompts)
    for fn in os.listdir(tmp_path):
        if fn.endswith(".aotx"):
            with open(os.path.join(tmp_path, fn), "wb") as f:
                f.write(b"not a pickle")
    reg = MetricsRegistry()
    events = []
    e2 = _engine(tiny, tmp_path, registry=reg, log=events.append)
    e2.warmup()
    outs = [t[:8] for t in e2.generate_all(prompts, max_new_tokens=8)]
    assert outs == refs
    errors = _counts(reg, "fstpu_aot_cache_errors_total")
    assert sum(errors.values()) >= 1, errors
    assert any(e.get("event") == "aot_cache_error" for e in events)
    # the corrupt files were replaced by fresh compiles
    e3_reg = MetricsRegistry()
    e3 = _engine(tiny, tmp_path, registry=e3_reg)
    e3.warmup()
    assert sum(_counts(e3_reg,
                       "fstpu_aot_cache_hits_total").values()) >= 4


def test_jax_version_mismatch_blob_recompiles(tmp_path):
    """A blob whose header names a different jax version must load as
    an error (counted) and recompile — never crash, never run a
    foreign executable."""
    reg = MetricsRegistry()
    cache = ExecutableCache(str(tmp_path), registry=reg)

    def f(x):
        return x - 5.0

    aval = jax.ShapeDtypeStruct((3,), jnp.float32)
    cached_compile(f, "t/v", aval, cache=cache, registry=reg)
    (path,) = [os.path.join(tmp_path, fn) for fn in os.listdir(tmp_path)
               if fn.endswith(".aotx")]
    with open(path, "rb") as fh:
        blob = pickle.load(fh)
    blob["jax"] = "0.0.0-from-the-past"
    with open(path, "wb") as fh:
        pickle.dump(blob, fh)
    exe = cached_compile(f, "t/v", aval, cache=cache, registry=reg)
    np.testing.assert_allclose(np.asarray(exe(jnp.zeros(3))),
                               [-5.0, -5.0, -5.0])
    assert _counts(reg, "fstpu_aot_cache_errors_total") == {"t/v": 1}
    assert _counts(reg, "fstpu_aot_cache_misses_total") == {"t/v": 2}


# ---- warmup manifest ----------------------------------------------------

def test_avals_encode_decode_roundtrip():
    args = ({"w": np.zeros((3, 4), np.float32),
             "b": jnp.ones((4,), jnp.int32)},
            np.int32(7), [np.zeros((2,), bool), None],
            (np.float64(1.5),))
    dec = decode_avals(encode_avals(args))
    assert isinstance(dec, tuple) and isinstance(dec[2], list)
    assert dec[0]["w"].shape == (3, 4)
    assert str(dec[0]["w"].dtype) == "float32"
    assert dec[1].shape == () and str(dec[1].dtype) == "int32"
    assert str(dec[2][0].dtype) == "bool" and dec[2][1] is None
    assert str(dec[3][0].dtype) == "float64"


def test_manifest_records_and_replays(tmp_path):
    reg = MetricsRegistry()
    setup = AotSetup(AotConfig(cache_dir=str(tmp_path)), registry=reg)
    cf = setup.wrap(lambda a, b: a * b, "t/mul")
    cf(jnp.arange(4.0), jnp.ones(4))
    man = json.load(open(os.path.join(tmp_path,
                                      "warmup_manifest.json")))
    assert len(man["entries"]) == 1
    entry = man["entries"][0]
    assert entry["name"] == "t/mul"
    assert entry["key"] and entry["fingerprint"]

    # fresh "process": trusted replay adopts by key — no lower, no miss
    reg2 = MetricsRegistry()
    setup2 = AotSetup(AotConfig(cache_dir=str(tmp_path)),
                      registry=reg2)
    cf2 = setup2.wrap(lambda a, b: a * b, "t/mul")
    summary = setup2.replay({"t/mul": cf2})
    assert summary["adopted"] == 1 and summary["failed"] == 0
    assert cf2._cache_size() == 1
    assert _counts(reg2, "fstpu_aot_cache_misses_total") == {}
    out = cf2(jnp.arange(4.0), jnp.full((4,), 2.0))
    np.testing.assert_allclose(np.asarray(out), [0.0, 2.0, 4.0, 6.0])


def test_manifest_fingerprint_drift_demotes_to_verified_replay(
        tmp_path):
    """A tampered/stale fingerprint must NOT adopt by key — replay
    falls back to lower-and-hash (still warming the function)."""
    setup = AotSetup(AotConfig(cache_dir=str(tmp_path)))
    cf = setup.wrap(lambda x: x + 2, "t/add")
    cf(jnp.arange(3.0))
    mpath = os.path.join(tmp_path, "warmup_manifest.json")
    man = json.load(open(mpath))
    man["entries"][0]["fingerprint"] = "stale-code-digest"
    with open(mpath, "w") as f:
        json.dump(man, f)

    reg = MetricsRegistry()
    setup2 = AotSetup(AotConfig(cache_dir=str(tmp_path)), registry=reg)
    cf2 = setup2.wrap(lambda x: x + 2, "t/add")
    summary = setup2.replay({"t/add": cf2})
    assert summary["adopted"] == 0 and summary["replayed"] == 1
    # verified path re-lowered and HIT the cache by content address
    assert sum(_counts(reg, "fstpu_aot_cache_hits_total").values()) == 1
    assert cf2._cache_size() == 1


def test_manifest_corrupt_file_starts_empty(tmp_path):
    path = os.path.join(tmp_path, "m.json")
    with open(path, "w") as f:
        f.write("{broken json")
    events = []
    man = WarmupManifest(path, record=True, log=events.append)
    assert len(man) == 0
    assert any(e.get("event") == "aot_manifest_error" for e in events)
    assert man.record("t/x", (np.zeros((2,), np.float32),))
    assert len(WarmupManifest(path)) == 1


def test_replay_skips_unknown_functions(tmp_path):
    setup = AotSetup(AotConfig(cache_dir=str(tmp_path)))
    cf = setup.wrap(lambda x: x, "t/known")
    cf(jnp.zeros(2))
    man = setup.manifest
    man.record("t/unknown", (np.zeros((2,), np.float32),))
    summary = man.replay({"t/known": cf}, trusted=False)
    assert summary["skipped"] == 1 and summary["failed"] == 0


# ---- LRU size cap -------------------------------------------------------

def test_lru_purge_evicts_least_recently_used(tmp_path):
    cache = ExecutableCache(str(tmp_path))
    for i, name in enumerate(("a", "b", "c")):
        p = cache.path_for(name, "k" * 8)
        with open(p, "wb") as f:
            f.write(b"x" * 100)
        os.utime(p, (1000 + i, 1000 + i))   # a oldest, c newest
    removed = cache.purge(max_bytes=250)
    assert [e.name for e in removed] == ["a"]
    assert {e.name for e in cache.entries()} == {"b", "c"}
    removed = cache.purge(drop_all=True)
    assert len(removed) == 2 and cache.entries() == []


def test_store_triggers_size_cap(tmp_path):
    reg = MetricsRegistry()
    cache = ExecutableCache(str(tmp_path), max_bytes=1, registry=reg)

    def f(x):
        return x * 2

    cached_compile(f, "t/cap", jax.ShapeDtypeStruct((2,), jnp.float32),
                   cache=cache, registry=reg)
    # the just-stored blob immediately exceeds the 1-byte cap
    assert cache.entries() == []


# ---- CLI ----------------------------------------------------------------

def test_cli_ls_and_purge(tmp_path, capsys):
    from fengshen_tpu.aot.__main__ import main as cli
    d = str(tmp_path / "cache")
    cache = ExecutableCache(d)
    cached_compile(lambda x: x + 1, "t/cli",
                   jax.ShapeDtypeStruct((2,), jnp.float32), cache=cache)
    assert cli(["ls", "--cache-dir", d]) == 0
    out = capsys.readouterr().out
    assert "t-cli" in out and "total: 1 executables" in out
    assert cli(["ls", "--cache-dir", d, "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["entries"][0]["name"] == "t-cli"
    assert doc["total_bytes"] > 0
    assert cli(["purge", "--cache-dir", d, "--all"]) == 0
    assert "purged 1 executables" in capsys.readouterr().out
    assert cli(["ls", "--cache-dir", d]) == 0
    assert "empty" in capsys.readouterr().out


def test_cli_purge_requires_a_mode(tmp_path):
    from fengshen_tpu.aot.__main__ import main as cli
    assert cli(["purge", "--cache-dir", str(tmp_path)]) == 2


def test_cli_warm_usage_errors(tmp_path):
    from fengshen_tpu.aot.__main__ import main as cli
    assert cli(["warm", "--config",
                str(tmp_path / "missing.json")]) == 2
    cfg = tmp_path / "server.json"
    cfg.write_text(json.dumps({"PIPELINE": {"task": "text_generation"}}))
    # no AOT block and no --cache-dir override → nothing to pre-bake
    assert cli(["warm", "--config", str(cfg)]) == 2


# ---- /healthz readiness -------------------------------------------------

class _DummyPipeline:
    def __call__(self, text, **kw):
        return "ok:" + text


def _get(url):
    try:
        with urllib.request.urlopen(url, timeout=10) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_healthz_503_until_ready_stdlib():
    from fengshen_tpu.api.main import (PipelineConfig, ServerConfig,
                                       build_stdlib_server)
    ready = threading.Event()
    server = build_stdlib_server(
        ServerConfig(host="127.0.0.1", port=0),
        PipelineConfig(task="text_classification"),
        pipeline=_DummyPipeline(), ready=ready)
    port = server.server_address[1]
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    try:
        code, body = _get(f"http://127.0.0.1:{port}/healthz")
        assert code == 503 and body["status"] == "warming"
        # ISSUE 10: the 503 body names the ready/reason contract the
        # fleet router keys on (warmup = the way in, vs draining)
        assert body["ready"] is False and body["reason"] == "warmup"
        ready.set()
        code, body = _get(f"http://127.0.0.1:{port}/healthz")
        assert code == 200 and body["status"] == "ok"
        assert body["ready"] is True
    finally:
        server.shutdown()


def test_healthz_defaults_to_ready_stdlib():
    """ready=None (every existing caller) keeps the old always-200
    behavior."""
    from fengshen_tpu.api.main import (PipelineConfig, ServerConfig,
                                       build_stdlib_server)
    server = build_stdlib_server(
        ServerConfig(host="127.0.0.1", port=0),
        PipelineConfig(task="text_classification"),
        pipeline=_DummyPipeline())
    port = server.server_address[1]
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    try:
        code, body = _get(f"http://127.0.0.1:{port}/healthz")
        assert code == 200 and body["status"] == "ok"
    finally:
        server.shutdown()


def test_healthz_503_until_ready_fastapi():
    fastapi = pytest.importorskip("fastapi")  # noqa: F841
    from fastapi.testclient import TestClient

    from fengshen_tpu.api.main import PipelineConfig, build_app
    ready = threading.Event()
    app = build_app(PipelineConfig(task="text_classification"),
                    pipeline=_DummyPipeline(), ready=ready)
    client = TestClient(app)
    r = client.get("/healthz")
    assert r.status_code == 503 and r.json()["status"] == "warming"
    # the fastapi path mirrors the stdlib ready/reason body (ISSUE 10)
    assert r.json()["ready"] is False and r.json()["reason"] == "warmup"
    ready.set()
    r = client.get("/healthz")
    assert r.status_code == 200 and r.json()["ready"] is True


# ---- warmup + build-info gauges ----------------------------------------

def test_build_info_and_warmup_gauges():
    from fengshen_tpu.observability import (get_registry,
                                            record_build_info,
                                            record_warmup_seconds)
    record_build_info()
    g = get_registry().get("fstpu_build_info")
    children = dict(g.children())
    assert (jax.__version__, jax.default_backend()) in children
    assert children[(jax.__version__, jax.default_backend())].value == 1

    record_warmup_seconds("test_phase", 1.25)
    w = get_registry().get("fstpu_warmup_seconds")
    assert dict(w.children())[("test_phase",)].value == 1.25


def test_engine_warmup_sets_global_gauge(tiny):
    from fengshen_tpu.observability import get_registry
    model, params = tiny
    eng = ContinuousBatchingEngine(
        model, params, EngineConfig(num_slots=1, buckets=(8,),
                                    max_new_tokens=4, max_queue=4))
    dt = eng.warmup()
    w = get_registry().get("fstpu_warmup_seconds")
    recorded = dict(w.children())[("engine",)].value
    assert recorded == pytest.approx(dt, rel=0.2)


def test_warmup_pipeline_sets_gauge():
    from fengshen_tpu.api.main import warmup_pipeline
    from fengshen_tpu.observability import get_registry
    dt = warmup_pipeline(_DummyPipeline(), "dummy")
    assert dt is not None
    w = get_registry().get("fstpu_warmup_seconds")
    assert ("pipeline",) in dict(w.children())


# ---- AOT config block plumbing -----------------------------------------

def test_server_config_aot_block(tmp_path):
    from fengshen_tpu.api.main import load_config
    cfg = tmp_path / "server.json"
    cfg.write_text(json.dumps({
        "SERVER": {"engine": "continuous"},
        "PIPELINE": {"task": "text_generation"},
        "AOT": {"cache_dir": "/tmp/x", "record": False}}))
    server_cfg, _ = load_config(str(cfg))
    assert server_cfg.aot_args == {"cache_dir": "/tmp/x",
                                   "record": False}
    # no AOT block → empty dict, engine runs plain jit
    cfg.write_text(json.dumps({"PIPELINE": {"task": "t"}}))
    server_cfg, _ = load_config(str(cfg))
    assert server_cfg.aot_args == {}


def test_create_continuous_engine_wires_aot(tiny, tmp_path):
    from fengshen_tpu.aot import CachedFunction as CF
    from fengshen_tpu.api.main import create_continuous_engine
    from fengshen_tpu.pipelines.text_generation import Pipeline

    model, params = tiny

    class Tok:
        eos_token_id = None
        pad_token_id = 0

        def encode(self, text):
            return [int(t) for t in text.split()]

        def decode(self, ids):
            return " ".join(str(t) for t in ids)

    pipe = Pipeline(module=model, params=params, tokenizer=Tok(),
                    max_new_tokens=4)
    engine = create_continuous_engine(
        pipe, {"num_slots": 1, "buckets": (8,)},
        aot_args={"cache_dir": str(tmp_path)})
    assert isinstance(engine._decode_jit, CF)
    engine2 = create_continuous_engine(pipe, {"num_slots": 1,
                                              "buckets": (8,)})
    assert not isinstance(engine2._decode_jit, CF)


def test_unpicklable_treedef_falls_back_to_flat_blob(tmp_path):
    """A program whose out tree carries unpicklable static metadata
    (the TrainState-with-optax-closures case) must still round-trip
    through the cache — stored flat, re-wrapped from the loader's
    Lowered — and stay invisible to the caller."""

    @jax.tree_util.register_pytree_node_class
    class Box:
        def __init__(self, x, fn):
            self.x, self.fn = x, fn

        def tree_flatten(self):
            return (self.x,), self.fn

        @classmethod
        def tree_unflatten(cls, aux, children):
            return cls(children[0], aux)

    local_fn = lambda v: v  # noqa: E731 — deliberately unpicklable aux

    def f(b, y):
        return Box(b.x + y, b.fn), (b.x * 2).sum()

    reg = MetricsRegistry()
    cache = ExecutableCache(str(tmp_path), registry=reg)
    box_aval = Box(jax.ShapeDtypeStruct((3,), jnp.float32), local_fn)
    y_aval = jax.ShapeDtypeStruct((3,), jnp.float32)
    cached_compile(f, "t/flat", box_aval, y_aval, cache=cache,
                   registry=reg)
    (path,) = [os.path.join(tmp_path, fn) for fn in os.listdir(tmp_path)
               if fn.endswith(".aotx")]
    with open(path, "rb") as fh:
        blob = pickle.load(fh)
    assert blob["tree_mode"] == "flat"
    assert blob["n_in"] == 2 and blob["n_out"] == 2

    exe = cached_compile(f, "t/flat", box_aval, y_aval, cache=cache,
                         registry=reg)
    assert _counts(reg, "fstpu_aot_cache_hits_total") == {"t/flat": 1}
    out_box, total = exe(Box(jnp.arange(3.0), local_fn), jnp.ones(3))
    assert isinstance(out_box, Box) and out_box.fn is local_fn
    np.testing.assert_allclose(np.asarray(out_box.x), [1.0, 2.0, 3.0])
    assert float(total) == 6.0

    # a flat blob is NOT adoptable without a Lowered (trusted replay
    # declines it) — and declining is a miss, not an error
    cf = CachedFunction(f, "t/flat", cache=cache, registry=reg)
    assert cf.adopt((box_aval, y_aval), blob["key"]) is False
    assert _counts(reg, "fstpu_aot_cache_errors_total") == {}


def test_failed_engine_warmup_still_starts_serve_loop(tiny, capsys):
    """A warmup crash must not leave a replica that reports ready while
    no serve loop drains its queue (every request would hang to its
    full timeout): the gate opens AND the engine starts, so requests
    compile lazily."""
    from fengshen_tpu.api.main import (PipelineConfig, ServerConfig,
                                       _start_warmup_thread)
    model, params = tiny
    eng = ContinuousBatchingEngine(
        model, params, EngineConfig(num_slots=1, buckets=(8,),
                                    max_new_tokens=4, max_queue=4))
    eng.warmup = lambda: (_ for _ in ()).throw(
        RuntimeError("compile OOM"))
    ready = _start_warmup_thread(
        ServerConfig(engine="continuous"),
        PipelineConfig(task="text_generation"), None, eng)
    assert ready.wait(30)
    try:
        assert eng._thread is not None and eng._thread.is_alive()
        req = eng.submit(np.asarray([5, 7], np.int32))
        assert req.wait(60) and req.state == "finished"
    finally:
        eng.stop()
    assert "warmup failed" in capsys.readouterr().out
