"""Launcher-matrix smoke parse (VERDICT r2 item 6): every .sh under
examples/ that invokes `python -m fengshen_tpu....` must pass only flags
the target module's argparse actually declares, and the zen2/t5/clue
dirs must match the reference shell counts.
"""

import glob
import importlib
import os
import re

import pytest

pytestmark = pytest.mark.slow  # full-fit/e2e lane: run with -m slow or no -m filter

EXAMPLES = os.path.join(os.path.dirname(__file__), "..", "fengshen_tpu",
                        "examples")


def _shells():
    out = []
    for path in sorted(glob.glob(os.path.join(EXAMPLES, "*", "*.sh"))):
        text = open(path).read()
        m = re.search(r"python -m (fengshen_tpu[\w.]+)", text)
        if m:
            out.append((path, m.group(1), text))
    return out


def _declared_flags(module_name: str) -> set:
    """Build the module's full parser the way its main() does: shared
    trainer/data/module/checkpoint args + every add-args hook reachable
    from the driver, following one level of `from fengshen_tpu...
    import` delegation (pipelines live in models/, the clip finetune
    driver delegates to the pretrain main)."""
    import argparse
    import inspect

    parser = argparse.ArgumentParser()
    from fengshen_tpu.data import UniversalDataModule
    from fengshen_tpu.models.model_utils import add_module_args
    from fengshen_tpu.trainer import add_trainer_args
    from fengshen_tpu.utils import UniversalCheckpoint
    add_module_args(parser)
    add_trainer_args(parser)
    UniversalDataModule.add_data_specific_args(parser)
    UniversalCheckpoint.add_argparse_args(parser)

    seen_mods = set()

    def scan(name):
        if name in seen_mods:
            return
        seen_mods.add(name)
        try:
            mod = importlib.import_module(name)
            src = inspect.getsource(mod)
        except Exception:
            return
        for attr in dir(mod):
            obj = getattr(mod, attr)
            for hook in ("add_module_specific_args", "add_data_args",
                         "add_pipeline_specific_args", "pipelines_args"):
                fn = getattr(obj, hook, None)
                if callable(fn) and getattr(
                        obj, "__module__", "").startswith("fengshen_tpu"):
                    try:
                        fn(parser)
                    except argparse.ArgumentError:
                        pass  # overlapping group flags
        for m in re.finditer(r"add_argument\(\s*\"(--[\w-]+)\"", src):
            try:
                parser.add_argument(m.group(1))
            except argparse.ArgumentError:
                pass
        for m in re.finditer(r"from (fengshen_tpu[\w.]+) import", src):
            scan(m.group(1))

    scan(module_name)
    return {o for a in parser._actions for o in a.option_strings}


@pytest.mark.parametrize("path,module,text", _shells(),
                         ids=lambda v: os.path.basename(v)
                         if isinstance(v, str) and v.endswith(".sh")
                         else None)
def test_shell_flags_exist(path, module, text):
    declared = _declared_flags(module)
    used = set(re.findall(r"(--[\w-]+)", text))
    # strip shell-level false positives (long options inside comments
    # that match declared flags are fine to check too)
    unknown = {f for f in used if f not in declared}
    assert not unknown, (
        f"{os.path.basename(path)} passes flags unknown to {module}: "
        f"{sorted(unknown)}")


def test_matrix_counts_match_reference():
    """Reference dirs: zen2_finetune 22 shells, zen1_finetune 2,
    pretrain_t5 model-scale configs 4 (57M/700M/large/10B), clue1.1
    run_clue_{unimc,ubert}."""
    zen2 = glob.glob(os.path.join(EXAMPLES, "zen2_finetune", "*.sh"))
    assert len([p for p in zen2
                if re.match(r"(fs|ner)_zen2_(base|large)_",
                            os.path.basename(p))]) == 22
    zen1 = glob.glob(os.path.join(EXAMPLES, "zen1_finetune", "*.sh"))
    assert len(zen1) >= 2
    t5 = [os.path.basename(p) for p in
          glob.glob(os.path.join(EXAMPLES, "pretrain_t5", "*.sh"))]
    for name in ("pretrain_randeng_t5_char_57M.sh",
                 "pretrain_randeng_t5_char_700M.sh",
                 "pretrain_randeng_t5_large.sh",
                 "pretrain_randeng_t5_char_10B.sh"):
        assert name in t5
    clue = [os.path.basename(p) for p in
            glob.glob(os.path.join(EXAMPLES, "clue1_1", "*.sh"))]
    assert "run_clue_unimc.sh" in clue and "run_clue_ubert.sh" in clue


def test_launcher_listing_diff_empty():
    """Round-4 closure (VERDICT r3 missing #1): every reference shell
    name has a same-name counterpart under examples/ or launchers/."""
    ref = {os.path.basename(p) for p in glob.glob(
        "/root/reference/fengshen/examples/**/*.sh", recursive=True)}
    if not ref:
        pytest.skip("reference tree not present")
    mine = {os.path.basename(p) for p in glob.glob(
        os.path.join(EXAMPLES, "**", "*.sh"), recursive=True)}
    mine |= {os.path.basename(p) for p in glob.glob(
        os.path.join(EXAMPLES, "..", "..", "launchers", "*.sh"))}
    missing = sorted(ref - mine)
    assert not missing, f"reference shells without counterpart: {missing}"


def test_run_clue_unimc_e2e(tmp_path, monkeypatch):
    """The clue1.1 UniMC recipe driver end-to-end on synthetic tnews
    data with a tiny config."""
    import json

    from transformers import BertTokenizer

    from fengshen_tpu.examples.clue1_1 import run_clue_unimc
    from fengshen_tpu.models.megatron_bert import MegatronBertConfig

    chars = list("体育财经故事文化娱乐房产汽车教育科技军事旅游国际股票农业电竞"
                 "运动员比赛股市经济新闻标题测试")
    vocab = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]", "是", "否"] + \
        sorted(set(chars))
    (tmp_path / "vocab.txt").write_text("\n".join(vocab))
    tok = BertTokenizer(str(tmp_path / "vocab.txt"))
    model_dir = tmp_path / "model"
    model_dir.mkdir()
    tok.save_pretrained(str(model_dir))
    MegatronBertConfig(
        vocab_size=len(vocab), hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, type_vocab_size=2,
        dtype="float32").save_pretrained(str(model_dir))

    data_dir = tmp_path / "tnews"
    data_dir.mkdir()
    rows = [{"sentence": "运动员比赛", "label": "103", "id": i}
            for i in range(4)]
    for split in ("train.json", "dev.json", "test.json"):
        with open(data_dir / split, "w") as f:
            for r in rows:
                f.write(json.dumps(r, ensure_ascii=False) + "\n")

    out = tmp_path / "predict.json"
    run_clue_unimc.main([
        "--task", "tnews", "--data_dir", str(data_dir),
        "--model_path", str(model_dir),
        "--output_path", str(out), "--max_length", "64",
        "--max_steps", "2", "--train_batchsize", "2",
        "--log_every_n_steps", "1", "--warmup_steps", "1",
        "--default_root_dir", str(tmp_path / "runs"),
        "--save_ckpt_path", str(tmp_path / "ckpt"),
        "--load_ckpt_path", str(tmp_path / "ckpt")])
    preds = [json.loads(l) for l in open(out)]
    assert len(preds) == 4
    tnews_ids = {"100", "101", "102", "103", "104", "106", "107",
                 "108", "109", "110", "112", "113", "114", "115",
                 "116"}
    assert all(p["label"] in tnews_ids for p in preds)


def test_cluedata2unidata_converters(tmp_path):
    """Raw CLUE rows → the reference's exact UniMC items (question
    strings, option phrasing, text augmentations) per task."""
    import json

    from fengshen_tpu.examples.clue1_1 import cluedata2unidata as c2u

    t = c2u.convert_tnews({"sentence": "股市大涨", "label": "114",
                           "label_desc": "news_stock", "id": 7})
    assert t["question"] == "下面新闻属于哪一个类别？"
    assert t["choice"][t["label"]] == "股票" and t["answer"] == "股票"

    a = c2u.convert_afqmc({"sentence1": "花呗如何还款",
                           "sentence2": "花呗怎么还钱", "label": "1"})
    assert a["choice"] == ["不相似", "相似"] and a["label"] == 1

    o = c2u.convert_ocnli({"sentence1": "他在北京", "sentence2": "他在中国",
                           "label": "entailment"})
    assert o["choice"][o["label"]] == "蕴含"

    w = c2u.convert_wsc({
        "text": "小明告诉小红他很高兴",
        "target": {"span1_index": 0, "span1_text": "小明",
                   "span2_index": 6, "span2_text": "他"},
        "label": "true"})
    assert "[小明]" in w["texta"] and "_他_" in w["texta"]
    assert w["choice"][w["label"]] == "他是小明"

    s = c2u.convert_csl({"abst": "本文研究了深度学习模型的压缩方法",
                         "keyword": ["深度学习", "压缩"], "label": "1"})
    assert s["choice"][s["label"]].startswith("可以使用深度学习、压缩")
    assert s["texta"].endswith("本文研究了深度学习模型的压缩方法")

    c3 = c2u.convert_c3([["第一句。", "第二句。"],
                         [{"question": "问题？", "id": "q-77",
                           "choice": ["甲", "乙"], "answer": "乙"}],
                         "c3-id"])
    assert len(c3) == 1 and c3[0]["label"] == 1
    assert c3[0]["id"] == "q-77"  # per-question, not the doc id

    ch = c2u.convert_chid(
        {"content": ["这件事#idiom000001#，大家都明白。"],
         "candidates": ["一目了然", "一知半解"]},
        {"#idiom000001#": 0})
    assert len(ch) == 1 and ch[0]["label"] == 0
    assert "____" in ch[0]["texta"]

    # end-to-end file conversion + the driver's pass-through
    raw = tmp_path / "raw"
    raw.mkdir()
    with open(raw / "train.json", "w") as f:
        f.write(json.dumps({"sentence": "股市大涨", "label": "114",
                            "label_desc": "news_stock", "id": 1},
                           ensure_ascii=False) + "\n")
    out_dir = tmp_path / "uni"
    c2u.main(["--task", "tnews", "--input_dir", str(raw),
              "--output_dir", str(out_dir)])
    rows = [json.loads(l) for l in open(out_dir / "train.json")]
    assert rows and rows[0]["choice"][rows[0]["label"]] == "股票"

    from fengshen_tpu.examples.clue1_1.run_clue_unimc import to_unimc
    passed = to_unimc("tnews", rows, [], [])
    assert passed is rows  # converted rows pass through unchanged


def test_cluedata2unidata_label_hygiene():
    """Unmapped labels (OCNLI '-') drop the row; absent labels (test
    split) emit no label key; converter option order agrees with
    run_clue_unimc's TASK_LABELS so written prediction ids are right."""
    from fengshen_tpu.examples.clue1_1 import cluedata2unidata as c2u
    from fengshen_tpu.examples.clue1_1.run_clue_unimc import TASK_LABELS

    # '-' (no consensus) must be dropped, not trained as class 0
    assert c2u.convert_ocnli({"sentence1": "a", "sentence2": "b",
                              "label": "-"}) is c2u._SKIP
    # test rows carry no label key at all
    t = c2u.convert_tnews({"sentence": "x", "id": 1})
    assert "label" not in t
    # order agreement: option index i ↔ TASK_LABELS id i
    for task, conv, probe in (
            ("ocnli", c2u.convert_ocnli,
             lambda lid: {"sentence1": "a", "sentence2": "b",
                          "label": lid}),
            ("wsc", c2u.convert_wsc,
             lambda lid: {"text": "小明说他好",
                          "target": {"span1_index": 0,
                                     "span1_text": "小明",
                                     "span2_index": 3,
                                     "span2_text": "他"},
                          "label": lid}),
            ("csl", c2u.convert_csl,
             lambda lid: {"abst": "研究", "keyword": ["研"],
                          "label": lid})):
        label_ids, _ = TASK_LABELS[task]
        for i, lid in enumerate(label_ids):
            item = conv(probe(lid))
            assert item["label"] == i, (task, lid, item)


def test_run_clue_unimc_chid_c3_submission_formats(tmp_path, monkeypatch):
    """chid submits ONE dict {tag: index}; c3 submits option indices —
    the reference predict2submit formats."""
    import json

    from fengshen_tpu.examples.clue1_1 import run_clue_unimc as drv

    from fengshen_tpu.models.unimc.modeling_unimc import UniMCPipelines

    class FakePipe:
        add_pipeline_specific_args = staticmethod(
            UniMCPipelines.add_pipeline_specific_args)

        def __init__(self, args=None, model=None):
            pass

        def train(self, *a, **k):
            raise AssertionError("no train data given")

        def predict(self, rows):
            return [1] * len(rows)

    monkeypatch.setattr(
        "fengshen_tpu.models.unimc.modeling_unimc.UniMCPipelines",
        FakePipe)

    data = tmp_path / "chid"
    data.mkdir()
    rows = [{"texta": "这件事____。", "textb": "", "question": "",
             "choice": ["一目了然", "一知半解"], "answer": "",
             "id": f"#idiom00000{i}#"} for i in range(3)]
    with open(data / "test.json", "w") as f:
        for r in rows:
            f.write(json.dumps(r, ensure_ascii=False) + "\n")
    out = tmp_path / "chid_pred.json"
    drv.main(["--task", "chid", "--data_dir", str(data),
              "--output_path", str(out)])
    sub = json.loads(open(out).read())
    assert sub == {f"#idiom00000{i}#": 1 for i in range(3)}

    data2 = tmp_path / "c3"
    data2.mkdir()
    rows = [{"texta": "文。", "textb": "", "question": "问？",
             "choice": ["甲", "乙", "丙"], "answer": "", "id": i}
            for i in range(2)]
    with open(data2 / "test.json", "w") as f:
        for r in rows:
            f.write(json.dumps(r, ensure_ascii=False) + "\n")
    out2 = tmp_path / "c3_pred.json"
    drv.main(["--task", "c3", "--data_dir", str(data2),
              "--output_path", str(out2)])
    preds = [json.loads(l) for l in open(out2)]
    assert preds == [{"id": 0, "label": 1}, {"id": 1, "label": 1}]
