"""Launcher-matrix smoke parse (VERDICT r2 item 6): every .sh under
examples/ that invokes `python -m fengshen_tpu....` must pass only flags
the target module's argparse actually declares, and the zen2/t5/clue
dirs must match the reference shell counts.
"""

import glob
import importlib
import os
import re

import pytest

pytestmark = pytest.mark.slow  # full-fit/e2e lane: run with -m slow or no -m filter

EXAMPLES = os.path.join(os.path.dirname(__file__), "..", "fengshen_tpu",
                        "examples")


def _shells():
    out = []
    for path in sorted(glob.glob(os.path.join(EXAMPLES, "*", "*.sh"))):
        text = open(path).read()
        m = re.search(r"python -m (fengshen_tpu[\w.]+)", text)
        if m:
            out.append((path, m.group(1), text))
    return out


def _declared_flags(module_name: str) -> set:
    """Build the module's full parser the way its main() does: shared
    trainer/data/module/checkpoint args + every add-args hook reachable
    from the driver, following one level of `from fengshen_tpu...
    import` delegation (pipelines live in models/, the clip finetune
    driver delegates to the pretrain main)."""
    import argparse
    import inspect

    parser = argparse.ArgumentParser()
    from fengshen_tpu.data import UniversalDataModule
    from fengshen_tpu.models.model_utils import add_module_args
    from fengshen_tpu.trainer import add_trainer_args
    from fengshen_tpu.utils import UniversalCheckpoint
    add_module_args(parser)
    add_trainer_args(parser)
    UniversalDataModule.add_data_specific_args(parser)
    UniversalCheckpoint.add_argparse_args(parser)

    seen_mods = set()

    def scan(name):
        if name in seen_mods:
            return
        seen_mods.add(name)
        try:
            mod = importlib.import_module(name)
            src = inspect.getsource(mod)
        except Exception:
            return
        for attr in dir(mod):
            obj = getattr(mod, attr)
            for hook in ("add_module_specific_args", "add_data_args",
                         "add_pipeline_specific_args", "pipelines_args"):
                fn = getattr(obj, hook, None)
                if callable(fn) and getattr(
                        obj, "__module__", "").startswith("fengshen_tpu"):
                    try:
                        fn(parser)
                    except argparse.ArgumentError:
                        pass  # overlapping group flags
        for m in re.finditer(r"add_argument\(\s*\"(--[\w-]+)\"", src):
            try:
                parser.add_argument(m.group(1))
            except argparse.ArgumentError:
                pass
        for m in re.finditer(r"from (fengshen_tpu[\w.]+) import", src):
            scan(m.group(1))

    scan(module_name)
    return {o for a in parser._actions for o in a.option_strings}


@pytest.mark.parametrize("path,module,text", _shells(),
                         ids=lambda v: os.path.basename(v)
                         if isinstance(v, str) and v.endswith(".sh")
                         else None)
def test_shell_flags_exist(path, module, text):
    declared = _declared_flags(module)
    used = set(re.findall(r"(--[\w-]+)", text))
    # strip shell-level false positives (long options inside comments
    # that match declared flags are fine to check too)
    unknown = {f for f in used if f not in declared}
    assert not unknown, (
        f"{os.path.basename(path)} passes flags unknown to {module}: "
        f"{sorted(unknown)}")


def test_matrix_counts_match_reference():
    """Reference dirs: zen2_finetune 22 shells, zen1_finetune 2,
    pretrain_t5 model-scale configs 4 (57M/700M/large/10B), clue1.1
    run_clue_{unimc,ubert}."""
    zen2 = glob.glob(os.path.join(EXAMPLES, "zen2_finetune", "*.sh"))
    assert len([p for p in zen2
                if re.match(r"(fs|ner)_zen2_(base|large)_",
                            os.path.basename(p))]) == 22
    zen1 = glob.glob(os.path.join(EXAMPLES, "zen1_finetune", "*.sh"))
    assert len(zen1) >= 2
    t5 = [os.path.basename(p) for p in
          glob.glob(os.path.join(EXAMPLES, "pretrain_t5", "*.sh"))]
    for name in ("pretrain_randeng_t5_char_57M.sh",
                 "pretrain_randeng_t5_char_700M.sh",
                 "pretrain_randeng_t5_large.sh",
                 "pretrain_randeng_t5_char_10B.sh"):
        assert name in t5
    clue = [os.path.basename(p) for p in
            glob.glob(os.path.join(EXAMPLES, "clue1_1", "*.sh"))]
    assert "run_clue_unimc.sh" in clue and "run_clue_ubert.sh" in clue


def test_run_clue_unimc_e2e(tmp_path, monkeypatch):
    """The clue1.1 UniMC recipe driver end-to-end on synthetic tnews
    data with a tiny config."""
    import json

    from transformers import BertTokenizer

    from fengshen_tpu.examples.clue1_1 import run_clue_unimc
    from fengshen_tpu.models.megatron_bert import MegatronBertConfig

    chars = list("体育财经故事文化娱乐房产汽车教育科技军事旅游国际股票农业电竞"
                 "运动员比赛股市经济新闻标题测试")
    vocab = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]", "是", "否"] + \
        sorted(set(chars))
    (tmp_path / "vocab.txt").write_text("\n".join(vocab))
    tok = BertTokenizer(str(tmp_path / "vocab.txt"))
    model_dir = tmp_path / "model"
    model_dir.mkdir()
    tok.save_pretrained(str(model_dir))
    MegatronBertConfig(
        vocab_size=len(vocab), hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, type_vocab_size=2,
        dtype="float32").save_pretrained(str(model_dir))

    data_dir = tmp_path / "tnews"
    data_dir.mkdir()
    rows = [{"sentence": "运动员比赛", "label": "103", "id": i}
            for i in range(4)]
    for split in ("train.json", "dev.json", "test.json"):
        with open(data_dir / split, "w") as f:
            for r in rows:
                f.write(json.dumps(r, ensure_ascii=False) + "\n")

    out = tmp_path / "predict.json"
    run_clue_unimc.main([
        "--task", "tnews", "--data_dir", str(data_dir),
        "--model_path", str(model_dir),
        "--output_path", str(out), "--max_length", "64",
        "--max_steps", "2", "--train_batchsize", "2",
        "--log_every_n_steps", "1", "--warmup_steps", "1",
        "--default_root_dir", str(tmp_path / "runs"),
        "--save_ckpt_path", str(tmp_path / "ckpt"),
        "--load_ckpt_path", str(tmp_path / "ckpt")])
    preds = [json.loads(l) for l in open(out)]
    assert len(preds) == 4
    tnews_ids = {"100", "101", "102", "103", "104", "106", "107",
                 "108", "109", "110", "112", "113", "114", "115",
                 "116"}
    assert all(p["label"] in tnews_ids for p in preds)
