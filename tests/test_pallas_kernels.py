"""Kernel layer (fengshen_tpu.ops.pallas): registry/probe mechanics,
XLA-fallback parity for every dispatch seam, and the bench row
contract.

Parity doctrine (docs/kernels.md): every Pallas kernel registers next
to the stock XLA lowering it replaces, the xla lowering is op-for-op
the pre-seam model code (so CPU tier-1 pins bit-identical decode), and
the Mosaic path is checked against it in interpret mode — the same
numerics the TPU kernel runs, executed on the CPU backend.
"""

import argparse
import dataclasses
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fengshen_tpu.ops.pallas import (FORCE_ENV, dispatch_table,
                                     get_kernel, kernel_choice,
                                     kernel_fingerprint, log_dispatch,
                                     probe)
from fengshen_tpu.ops.pallas.decode_attention import (
    decode_attention, pallas_decode_attention, pallas_decode_eligible,
    xla_decode_attention)


@pytest.fixture
def fresh_probe(monkeypatch):
    """Each force-env scenario re-probes; the cache key includes the
    env var so leaving it unset afterwards restores the real answer."""
    monkeypatch.delenv(FORCE_ENV, raising=False)
    yield monkeypatch
    probe(refresh=True)


# -- registry + probe ---------------------------------------------------


def test_probe_cached_and_forceable(fresh_probe):
    info = probe(refresh=True)
    assert info.backend == "cpu"
    assert not info.pallas_tpu
    assert "cpu" in info.reason
    # cached: the second call answers from the dict, same object
    assert probe() is info

    fresh_probe.setenv(FORCE_ENV, "pallas")
    forced = probe()
    assert forced.pallas_tpu and forced.forced == "pallas"
    fresh_probe.setenv(FORCE_ENV, "xla")
    assert not probe().pallas_tpu


def test_dispatch_table_and_fingerprint(fresh_probe):
    table = dispatch_table()
    for op in ("decode_attention", "fused_ce", "flash_attention",
               "block_sparse_attention"):
        assert table[op] == "xla"  # CPU backend: stock lowerings
    fp = kernel_fingerprint()
    assert fp.startswith("kernels=") and fp.endswith(";backend=cpu")
    assert "decode_attention:xla" in fp

    # the AOT-key contract: a forced-pallas process fingerprints
    # differently, so it can never replay an xla-dispatch executable
    fresh_probe.setenv(FORCE_ENV, "pallas")
    assert "decode_attention:pallas" in kernel_fingerprint()


def test_get_kernel_resolution(fresh_probe):
    assert get_kernel("decode_attention") is xla_decode_attention
    assert get_kernel("decode_attention",
                      "pallas") is pallas_decode_attention
    with pytest.raises(KeyError):
        get_kernel("nonexistent_op")
    with pytest.raises(KeyError):
        # block-sparse's fallback lives in ops.attention, not here
        get_kernel("block_sparse_attention", "xla")


def test_log_dispatch_event_and_gauge(fresh_probe):
    from fengshen_tpu.observability.registry import MetricsRegistry

    events = []
    reg = MetricsRegistry()
    table = log_dispatch(events.append, registry=reg)
    assert table == dispatch_table()
    (event,) = events
    assert event["event"] == "kernel_dispatch"
    assert event["table"]["decode_attention"] == "xla"
    assert event["backend"] == "cpu" and event["reason"]
    gauge = reg.gauge("fstpu_kernel_dispatch", "",
                      labelnames=("op", "impl"))
    assert gauge.labels("decode_attention", "xla").value == 1.0
    assert gauge.labels("decode_attention", "pallas").value == 0.0


# -- decode attention: the stock-math pin -------------------------------


def _stock_decode(q, k, v, valid, k_scale=None, v_scale=None,
                  block_table=None, dt=jnp.float32):
    """The pre-seam model path, inlined from what
    `_update_paged_cache`/`_update_cache` + the attention call used to
    do: take-gather, dequantize, GQA repeat, dense attention."""
    from fengshen_tpu.ops.attention import dot_product_attention
    from fengshen_tpu.ops.int8_matmul import dequantize_kv

    if block_table is not None:
        nb, bs = k.shape[:2]
        batch = q.shape[0]
        idx = ((block_table * bs)[:, :, None] +
               jnp.arange(bs)[None, None, :]).reshape(batch, -1)
        k = jnp.take(k.reshape(nb * bs, *k.shape[2:]), idx, axis=0)
        v = jnp.take(v.reshape(nb * bs, *v.shape[2:]), idx, axis=0)
        if k_scale is not None:
            ks = jnp.take(k_scale.reshape(nb * bs, -1), idx, axis=0)
            vs = jnp.take(v_scale.reshape(nb * bs, -1), idx, axis=0)
            k, v = dequantize_kv(k, ks, dt), dequantize_kv(v, vs, dt)
    elif k_scale is not None:
        k = dequantize_kv(k, k_scale, dt)
        v = dequantize_kv(v, v_scale, dt)
    rep = q.shape[2] // k.shape[2]
    if rep > 1:
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    return dot_product_attention(q, k, v, mask=valid[:, None])


def _decode_case(layout, quant, s, rng, batch=2, n_heads=4, kv_heads=2,
                 head_dim=128, block_size=128, blocks_per_lane=2):
    """One (layout, dtype, spec_mode) decode combo's operands."""
    virt = block_size * blocks_per_lane
    q = jnp.asarray(rng.randn(batch, s, n_heads, head_dim) * 0.3,
                    jnp.float32)
    ctx = virt - 37  # ragged fill: the last block is partial
    valid = jnp.asarray(
        np.broadcast_to(np.arange(virt) < ctx, (batch, s, virt)).copy())
    kw = {}
    if layout == "paged":
        nb = batch * blocks_per_lane
        shape = (nb, block_size, kv_heads, head_dim)
        kw["block_table"] = jnp.asarray(
            rng.permutation(nb).reshape(batch, blocks_per_lane),
            jnp.int32)
    else:
        shape = (batch, virt, kv_heads, head_dim)
    if quant:
        k = jnp.asarray(rng.randint(-127, 128, shape), jnp.int8)
        v = jnp.asarray(rng.randint(-127, 128, shape), jnp.int8)
        kw["k_scale"] = jnp.asarray(rng.rand(*shape[:-1]) * 0.02 + 0.001,
                                    jnp.float32)
        kw["v_scale"] = jnp.asarray(rng.rand(*shape[:-1]) * 0.02 + 0.001,
                                    jnp.float32)
    else:
        k = jnp.asarray(rng.randn(*shape) * 0.3, jnp.float32)
        v = jnp.asarray(rng.randn(*shape) * 0.3, jnp.float32)
    return q, k, v, valid, kw


@pytest.mark.parametrize("layout", ["slot", "paged"])
@pytest.mark.parametrize("quant", [False, True])
@pytest.mark.parametrize("s", [1, 4])  # decode tick / spec-verify window
def test_xla_decode_is_the_stock_math(layout, quant, s):
    """The dispatcher's xla lowering must be BITWISE the pre-seam
    model sequence on every (layout, dtype, spec_mode) combo — this is
    what makes greedy decode through the seam token-identical."""
    rng = np.random.RandomState(hash((layout, quant, s)) % 2**31)
    q, k, v, valid, kw = _decode_case(layout, quant, s, rng)
    seam = decode_attention(q, k, v, valid, **kw)
    stock = _stock_decode(q, k, v, valid,
                          k_scale=kw.get("k_scale"),
                          v_scale=kw.get("v_scale"),
                          block_table=kw.get("block_table"))
    assert seam.shape == q.shape
    np.testing.assert_array_equal(np.asarray(seam), np.asarray(stock))


@pytest.mark.parametrize("layout", ["slot", "paged"])
@pytest.mark.parametrize("quant", [False, True])
@pytest.mark.parametrize("s", [1, 4])
def test_pallas_decode_interpret_parity(layout, quant, s):
    """The Mosaic kernel (interpret mode — same numerics the TPU
    compiles, run on CPU) against the stock lowering: fp32 tight, int8
    margin-aware (both paths round through the same dequant dtype, so
    the tolerance covers only the online-softmax reassociation)."""
    rng = np.random.RandomState(100 + hash((layout, quant, s)) % 2**31)
    q, k, v, valid, kw = _decode_case(layout, quant, s, rng)
    assert pallas_decode_eligible(q, k, v,
                                  block_table=kw.get("block_table"))
    ref = xla_decode_attention(q, k, v, valid, **kw)
    out = pallas_decode_attention(q, k, v, valid, interpret=True, **kw)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_decode_dispatcher_eligibility():
    """Ineligible shapes (tiny pages, odd head_dim, prefill-length
    windows) stay on the xla lowering instead of erroring."""
    rng = np.random.RandomState(7)
    q = jnp.asarray(rng.randn(2, 1, 4, 64), jnp.float32)  # D=64
    k = jnp.asarray(rng.randn(2, 256, 2, 64), jnp.float32)
    assert not pallas_decode_eligible(q, k, k)
    q2 = jnp.asarray(rng.randn(2, 16, 4, 128), jnp.float32)  # S=16
    k2 = jnp.asarray(rng.randn(2, 256, 2, 128), jnp.float32)
    assert not pallas_decode_eligible(q2, k2, k2)
    # eligible shape, impl override pins each path explicitly
    q3, k3, v3, valid, kw = _decode_case("slot", False, 1,
                                         np.random.RandomState(8))
    a = decode_attention(q3, k3, v3, valid, impl="xla", **kw)
    b = decode_attention(q3, k3, v3, valid, impl="pallas",
                         interpret=True, **kw)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=2e-5, atol=2e-5)


# -- orphan adoption: flash + block-sparse fallback parity --------------


def test_flash_orphan_interpret_parity():
    """pallas_flash_attention (GQA, causal) vs the blockwise xla
    fallback it registers next to."""
    from fengshen_tpu.ops.flash_attention import blockwise_attention
    from fengshen_tpu.ops.pallas.flash_attention import (
        pallas_flash_attention)

    rng = np.random.RandomState(9)
    q = jnp.asarray(rng.randn(1, 256, 2, 128) * 0.3, jnp.float32)
    k = jnp.asarray(rng.randn(1, 256, 1, 128) * 0.3, jnp.float32)
    v = jnp.asarray(rng.randn(1, 256, 1, 128) * 0.3, jnp.float32)
    out = pallas_flash_attention(q, k, v, causal=True, blk_q=128,
                                 blk_k=128, interpret=True)
    ref = blockwise_attention(q, jnp.repeat(k, 2, 2),
                              jnp.repeat(v, 2, 2), causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_block_sparse_orphan_interpret_parity():
    """block_sparse_attention vs the dense expanded-mask fallback that
    ops.attention.dot_product_attention uses for ineligible shapes."""
    from fengshen_tpu.ops.attention import dot_product_attention
    from fengshen_tpu.ops.pallas.block_sparse_attention import (
        block_sparse_attention)

    rng = np.random.RandomState(10)
    blk, n = 128, 2
    q = jnp.asarray(rng.randn(1, blk * n, 2, 128) * 0.3, jnp.float32)
    k = jnp.asarray(rng.randn(1, blk * n, 2, 128) * 0.3, jnp.float32)
    v = jnp.asarray(rng.randn(1, blk * n, 2, 128) * 0.3, jnp.float32)
    layout = np.tril(np.ones((n, n), bool))
    out = block_sparse_attention(q, k, v, layout, blk, interpret=True)
    mask = jnp.asarray(np.kron(layout, np.ones((blk, blk), bool)))
    ref = dot_product_attention(q, k, v, mask=mask[None, None])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


# -- fused CE -----------------------------------------------------------


def _ce_case(rng, batch=2, seq=8, hidden_dim=128, vocab=256):
    hidden = jnp.asarray(rng.randn(batch, seq, hidden_dim) * 0.1,
                         jnp.float32)
    kernel = jnp.asarray(rng.randn(hidden_dim, vocab) * 0.1, jnp.float32)
    labels = np.asarray(rng.randint(0, vocab, (batch, seq)))
    # some ignored positions + some guaranteed-correct ones (argmax
    # labels) so n_valid AND n_correct both carry signal
    labels[0, :2] = -100
    greedy = np.asarray((hidden @ kernel).argmax(-1))
    labels[1, :3] = greedy[1, :3]
    return hidden, kernel, jnp.asarray(labels, jnp.int32)


def test_fused_ce_dispatch_is_stock_on_cpu():
    """fused_ce_loss through the seam == ops.fused_ce.fused_lm_head_ce
    bitwise (the xla lowering IS that function)."""
    from fengshen_tpu.ops.fused_ce import fused_lm_head_ce
    from fengshen_tpu.ops.pallas.fused_ce import fused_ce_loss

    hidden, kernel, labels = _ce_case(np.random.RandomState(11))
    seam = fused_ce_loss(hidden, kernel, labels, num_chunks=4)
    stock = fused_lm_head_ce(hidden, kernel, labels, num_chunks=4)
    for a, b in zip(seam, stock):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_pallas_fused_ce_interpret_parity_and_grads():
    """The Mosaic CE (interpret mode): loss/n_valid/n_correct and the
    custom-vjp grads against the stock chunked-scan lowering."""
    from fengshen_tpu.ops.fused_ce import fused_lm_head_ce
    from fengshen_tpu.ops.pallas.fused_ce import pallas_fused_ce

    hidden, kernel, labels = _ce_case(np.random.RandomState(12))
    loss, n_valid, n_correct = pallas_fused_ce(hidden, kernel, labels,
                                               interpret=True)
    ref_loss, ref_valid, ref_correct = fused_lm_head_ce(
        hidden, kernel, labels, num_chunks=4)
    assert int(n_valid) == int(ref_valid)
    assert int(n_correct) == int(ref_correct) and int(n_correct) >= 3
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-6)

    g_pallas = jax.grad(
        lambda h, w: pallas_fused_ce(h, w, labels, interpret=True)[0],
        argnums=(0, 1))(hidden, kernel)
    g_stock = jax.grad(
        lambda h, w: fused_lm_head_ce(h, w, labels, num_chunks=4)[0],
        argnums=(0, 1))(hidden, kernel)
    for gp, gs in zip(g_pallas, g_stock):
        np.testing.assert_allclose(np.asarray(gp), np.asarray(gs),
                                   rtol=1e-5, atol=1e-6)


def test_fused_vocab_parallel_ce_bitwise(mesh8):
    """The sharded-vocab fused CE against the unfused
    vocab_parallel_cross_entropy on the tier-1 mesh (tensor=2): the
    per-chunk mpu collectives are the SAME ops on the same rows, so
    the loss must be bit-equal, never just close — and the full
    [B, S, V] logits never materialize on the fused side."""
    from fengshen_tpu.parallel.cross_entropy import (
        fused_vocab_parallel_ce, vocab_parallel_cross_entropy)

    hidden, kernel, labels = _ce_case(np.random.RandomState(13),
                                      hidden_dim=16, vocab=64)
    loss, n_valid, n_correct = fused_vocab_parallel_ce(
        hidden, kernel, labels, num_chunks=4)
    ref_loss, ref_valid = vocab_parallel_cross_entropy(
        hidden @ kernel, labels)
    assert float(loss) == float(ref_loss)  # bitwise
    assert int(n_valid) == int(ref_valid)
    greedy = np.asarray((hidden @ kernel).argmax(-1))
    want_correct = int(((greedy == np.asarray(labels)) &
                        (np.asarray(labels) != -100)).sum())
    assert int(n_correct) == want_correct and want_correct >= 3

    g_fused = jax.grad(lambda h: fused_vocab_parallel_ce(
        h, kernel, labels, num_chunks=4)[0])(hidden)
    g_ref = jax.grad(lambda h: vocab_parallel_cross_entropy(
        h @ kernel, labels)[0])(hidden)
    np.testing.assert_allclose(np.asarray(g_fused), np.asarray(g_ref),
                               rtol=1e-6, atol=1e-7)


def test_trainer_routes_vocab_parallel_fused_ce(mesh8):
    """CausalLMModule under tensor parallelism with fused_ce_chunks:
    the pinned `_fused_ce_active` gate still reports False (replicated
    lever off), the NEW mode routes `vocab_parallel`, and the loss
    equals the plain unfused path."""
    from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from fengshen_tpu.trainer.modules import CausalLMModule

    base = LlamaConfig(vocab_size=64, hidden_size=32,
                       intermediate_size=64, num_hidden_layers=2,
                       num_attention_heads=4,
                       max_position_embeddings=32, dtype="float32")
    args = argparse.Namespace(max_seq_length=16)
    ids = jnp.asarray(np.random.RandomState(14).randint(0, 63, (2, 16)),
                      jnp.int32)
    batch = {"input_ids": ids}
    rng = jax.random.PRNGKey(0)

    plain = CausalLMModule(args, LlamaForCausalLM(base), base)
    params = plain.init_params(rng)
    cfg_f = dataclasses.replace(base, fused_ce_chunks=4)
    fused = CausalLMModule(args, LlamaForCausalLM(cfg_f), cfg_f)

    assert plain._fused_ce_mode() == "off"
    assert not fused._fused_ce_active()  # the pinned tensor-par gate
    assert fused._fused_ce_mode() == "vocab_parallel"

    l_p, m_p = plain.training_loss(params, batch, rng)
    l_f, m_f = fused.training_loss(params, batch, rng)
    np.testing.assert_allclose(float(l_p), float(l_f), rtol=1e-6)
    np.testing.assert_allclose(float(m_p["acc"]), float(m_f["acc"]),
                               rtol=1e-6)


# -- bench rows + benchdiff identity ------------------------------------


def test_kernel_bench_rows_smoke(monkeypatch):
    """The decode + fused-CE rungs run in-process on CPU and emit
    BENCH-schema rows carrying the kernel dispatch decision."""
    from fengshen_tpu.ops.pallas.bench import (bench_fused_ce,
                                               bench_paged_decode)

    monkeypatch.setenv("KERNEL_BENCH_ITERS", "2")
    monkeypatch.setenv("KERNEL_BENCH_BATCH", "2")
    monkeypatch.setenv("KERNEL_BENCH_SEQ", "64")
    monkeypatch.setenv("KERNEL_BENCH_HIDDEN", "64")
    monkeypatch.setenv("KERNEL_BENCH_VOCAB", "256")
    for row in (bench_paged_decode(), bench_fused_ce()):
        for key in ("metric", "value", "unit", "vs_baseline", "kernel",
                    "backend"):
            assert key in row, (row["metric"], key)
        assert row["kernel"] == "xla"  # CPU process
        assert row["value"] > 0


def test_benchdiff_kernel_rows_incomparable():
    """A Mosaic round and a stock-lowering round measure different
    programs: benchdiff must diff them as incomparable, never as a
    regression (same contract as offload placement / fleet replicas)."""
    from fengshen_tpu.observability.benchdiff import diff_rounds

    rounds = [
        (1, "BENCH_r01.json", {"rc": 0, "parsed": [
            {"metric": "kernel_paged_decode_tokens_per_sec",
             "value": 100.0, "unit": "tokens/s", "vs_baseline": 1.0,
             "kernel": "xla"}]}),
        (2, "BENCH_r02.json", {"rc": 0, "parsed": [
            {"metric": "kernel_paged_decode_tokens_per_sec",
             "value": 5000.0, "unit": "tokens/s", "vs_baseline": 3.0,
             "kernel": "pallas"}]}),
        (3, "BENCH_r03.json", {"rc": 0, "parsed": [
            {"metric": "kernel_paged_decode_tokens_per_sec",
             "value": 4000.0, "unit": "tokens/s", "vs_baseline": 2.4,
             "kernel": "pallas"}]}),
    ]
    report = diff_rounds(rounds)
    statuses = {(c["round"], c["status"])
                for c in report["comparisons"]}
    assert (2, "incomparable") in statuses  # xla -> pallas: new program
    assert (3, "regression") in statuses    # pallas -> pallas: honest
    assert report["verdict"] == "REGRESSED"


def test_engine_aot_key_carries_kernel_fingerprint():
    """serving/engine.py folds kernel_fingerprint() into the AOT cache
    identity — source-level pin that a pallas-dispatch process can
    never replay an xla-dispatch executable (docs/aot_cache.md)."""
    import inspect

    from fengshen_tpu.serving import engine

    src = inspect.getsource(engine)
    assert "kernel_fingerprint()" in src
