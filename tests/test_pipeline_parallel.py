"""GPipe pipeline-parallel schedule tests (CPU 8-device mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh
import pytest



from fengshen_tpu.parallel.pipeline import pipeline_apply

pytestmark = pytest.mark.slow  # full-fit/e2e lane: run with -m slow or no -m filter



def _mesh_pipe4():
    devs = np.asarray(jax.devices()[:8]).reshape(4, 2)
    return Mesh(devs, ("pipe", "data"))


def test_pipeline_matches_sequential():
    rng = np.random.RandomState(0)
    n_stages, n_micro, mb, dim = 4, 6, 2, 8
    ws = jnp.asarray(rng.randn(n_stages, dim, dim) * 0.3, jnp.float32)
    bs = jnp.asarray(rng.randn(n_stages, dim) * 0.1, jnp.float32)
    params = {"w": ws, "b": bs}
    x = jnp.asarray(rng.randn(n_micro, mb, dim), jnp.float32)

    def stage_fn(p, h):
        return jnp.tanh(h @ p["w"] + p["b"])

    # sequential reference
    ref = x
    for s in range(n_stages):
        ref = jax.vmap(lambda h: stage_fn(
            {"w": ws[s], "b": bs[s]}, h))(ref)

    out = pipeline_apply(stage_fn, params, x, mesh=_mesh_pipe4())
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_pipeline_grad_flows():
    rng = np.random.RandomState(1)
    n_stages, n_micro, mb, dim = 4, 4, 2, 4
    params = {"w": jnp.asarray(rng.randn(n_stages, dim, dim) * 0.3,
                               jnp.float32)}
    x = jnp.asarray(rng.randn(n_micro, mb, dim), jnp.float32)
    mesh = _mesh_pipe4()

    def stage_fn(p, h):
        return jnp.tanh(h @ p["w"])

    def loss(p):
        out = pipeline_apply(stage_fn, p, x, mesh=mesh)
        return jnp.sum(out ** 2)

    g = jax.grad(loss)(params)
    assert np.isfinite(np.asarray(g["w"])).all()
    # every stage's weights receive gradient
    per_stage = np.abs(np.asarray(g["w"])).sum(axis=(1, 2))
    assert (per_stage > 0).all()


def test_1f1b_matches_sequential_grads():
    """1F1B fwd/bwd schedule: loss and stacked grads must equal plain
    autodiff of the sequential stage composition (VERDICT r1 item 8)."""
    import numpy as np
    from fengshen_tpu.parallel.pipeline import pipeline_train_step_1f1b

    n_stages, n_micro, mb, dim = 4, 6, 2, 8
    devices = np.asarray(jax.devices()[:4]).reshape(4)
    mesh = jax.sharding.Mesh(devices, ("pipe",))
    rng = np.random.RandomState(0)
    params = {"w": jnp.asarray(rng.randn(n_stages, dim, dim) * 0.3,
                               jnp.float32),
              "b": jnp.asarray(rng.randn(n_stages, dim) * 0.1,
                               jnp.float32)}
    xs = jnp.asarray(rng.randn(n_micro, mb, dim), jnp.float32)
    ys = jnp.asarray(rng.randn(n_micro, mb, dim), jnp.float32)

    def stage_fn(p, h):
        return jnp.tanh(h @ p["w"] + p["b"])

    def last_stage_loss(out, target):
        return jnp.mean((out - target) ** 2)

    loss, grads = pipeline_train_step_1f1b(
        stage_fn, last_stage_loss, params, xs, ys, mesh)

    def sequential_loss(p):
        def one(x, y):
            h = x
            for s in range(n_stages):
                ps = jax.tree_util.tree_map(lambda a: a[s], p)
                h = stage_fn(ps, h)
            return last_stage_loss(h, y)
        return jnp.mean(jax.vmap(one)(xs, ys))

    ref_loss = sequential_loss(params)
    ref_grads = jax.grad(sequential_loss)(params)
    np.testing.assert_allclose(float(loss), float(ref_loss), atol=1e-5)
    # grads are exactly d(loss)/d(params)
    for k in ("w", "b"):
        np.testing.assert_allclose(np.asarray(grads[k]),
                                   np.asarray(ref_grads[k]), atol=1e-4)


def test_trainer_fit_pipelined_llama_4stage(tmp_path):
    """End-to-end: Trainer.fit trains a 4-stage LLaMA slice through the
    GPipe pipeline over the 'pipe' mesh axis (VERDICT r1 item 8 done
    criterion)."""
    import argparse
    import json
    import numpy as np

    from fengshen_tpu.data import UniversalDataModule
    from fengshen_tpu.models.llama import LlamaConfig
    from fengshen_tpu.models.model_utils import add_module_args
    from fengshen_tpu.parallel import set_mesh
    from fengshen_tpu.trainer import Trainer, add_trainer_args
    from fengshen_tpu.trainer.modules import PipelinedCausalLMModule

    parser = argparse.ArgumentParser()
    add_module_args(parser)
    add_trainer_args(parser)
    UniversalDataModule.add_data_specific_args(parser)
    args = parser.parse_args([
        "--max_steps", "2", "--train_batchsize", "8",
        "--log_every_n_steps", "1", "--warmup_steps", "1",
        "--default_root_dir", str(tmp_path),
        "--pipe_model_parallel_size", "4",
        "--data_parallel_size", "2"])

    config = LlamaConfig(vocab_size=128, hidden_size=32,
                         intermediate_size=64, num_hidden_layers=4,
                         num_attention_heads=4,
                         max_position_embeddings=32, dtype="float32")
    rng = np.random.RandomState(0)
    rows = [{"input_ids": rng.randint(0, 127, 16).tolist()}
            for _ in range(16)]

    class ListDS:
        def __len__(self):
            return len(rows)

        def __getitem__(self, i):
            return rows[i]

    trainer = Trainer(args)  # builds the dp2 x pipe4 mesh
    module = PipelinedCausalLMModule(args, config)
    dm = UniversalDataModule(args=args, datasets={"train": ListDS()})
    state = trainer.fit(module, dm)
    assert int(state.step) == 2
    # stage dim is sharded over the pipe axis
    w = jax.tree_util.tree_leaves(state.params["layers"])[0]
    assert "pipe" in str(w.sharding.spec)
    lines = [json.loads(l) for l in open(tmp_path / "metrics.jsonl")]
    losses = [l["loss"] for l in lines if "loss" in l]
    assert len(losses) == 2 and all(np.isfinite(losses))
    set_mesh(None)


def test_trainer_fit_pipeline_composes_with_fsdp_tp(tmp_path):
    """pipe=2 composed with fsdp=2 and tensor=2 in ONE SPMD program
    (VERDICT r2 item 8): the pipeline shard_map is manual only over
    'pipe', so GSPMD still shards the within-stage math, and the stacked
    stage kernels carry pipe+fsdp+tensor shardings simultaneously."""
    import argparse
    import json
    import numpy as np

    from fengshen_tpu.data import UniversalDataModule
    from fengshen_tpu.models.llama import LlamaConfig
    from fengshen_tpu.models.model_utils import add_module_args
    from fengshen_tpu.parallel import set_mesh
    from fengshen_tpu.trainer import Trainer, add_trainer_args
    from fengshen_tpu.trainer.modules import PipelinedCausalLMModule

    parser = argparse.ArgumentParser()
    add_module_args(parser)
    add_trainer_args(parser)
    UniversalDataModule.add_data_specific_args(parser)
    args = parser.parse_args([
        "--max_steps", "1", "--train_batchsize", "4",
        "--log_every_n_steps", "1", "--warmup_steps", "1",
        "--default_root_dir", str(tmp_path),
        "--pipe_model_parallel_size", "2",
        "--fsdp_parallel_size", "2",
        "--tensor_model_parallel_size", "2",
        "--data_parallel_size", "1"])

    config = LlamaConfig(vocab_size=128, hidden_size=32,
                         intermediate_size=64, num_hidden_layers=2,
                         num_attention_heads=4,
                         max_position_embeddings=32, dtype="float32")
    rng = np.random.RandomState(0)
    rows = [{"input_ids": rng.randint(0, 127, 16).tolist()}
            for _ in range(8)]

    class ListDS:
        def __len__(self):
            return len(rows)

        def __getitem__(self, i):
            return rows[i]

    trainer = Trainer(args)
    module = PipelinedCausalLMModule(args, config)
    dm = UniversalDataModule(args=args, datasets={"train": ListDS()})
    state = trainer.fit(module, dm)
    assert int(state.step) == 1
    qk = state.params["layers"]["self_attn"]["q_proj"]["kernel"]
    spec = str(qk.sharding.spec)
    assert "pipe" in spec and "tensor" in spec and "fsdp" in spec, spec
    emb = state.params["embed"]["embedding"]
    assert "tensor" in str(emb.sharding.spec)
    lines = [json.loads(l) for l in open(tmp_path / "metrics.jsonl")]
    losses = [l["loss"] for l in lines if "loss" in l]
    assert losses and all(np.isfinite(losses))
    set_mesh(None)
