"""GPipe pipeline-parallel schedule tests (CPU 8-device mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from fengshen_tpu.parallel.pipeline import pipeline_apply


def _mesh_pipe4():
    devs = np.asarray(jax.devices()[:8]).reshape(4, 2)
    return Mesh(devs, ("pipe", "data"))


def test_pipeline_matches_sequential():
    rng = np.random.RandomState(0)
    n_stages, n_micro, mb, dim = 4, 6, 2, 8
    ws = jnp.asarray(rng.randn(n_stages, dim, dim) * 0.3, jnp.float32)
    bs = jnp.asarray(rng.randn(n_stages, dim) * 0.1, jnp.float32)
    params = {"w": ws, "b": bs}
    x = jnp.asarray(rng.randn(n_micro, mb, dim), jnp.float32)

    def stage_fn(p, h):
        return jnp.tanh(h @ p["w"] + p["b"])

    # sequential reference
    ref = x
    for s in range(n_stages):
        ref = jax.vmap(lambda h: stage_fn(
            {"w": ws[s], "b": bs[s]}, h))(ref)

    out = pipeline_apply(stage_fn, params, x, mesh=_mesh_pipe4())
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_pipeline_grad_flows():
    rng = np.random.RandomState(1)
    n_stages, n_micro, mb, dim = 4, 4, 2, 4
    params = {"w": jnp.asarray(rng.randn(n_stages, dim, dim) * 0.3,
                               jnp.float32)}
    x = jnp.asarray(rng.randn(n_micro, mb, dim), jnp.float32)
    mesh = _mesh_pipe4()

    def stage_fn(p, h):
        return jnp.tanh(h @ p["w"])

    def loss(p):
        out = pipeline_apply(stage_fn, p, x, mesh=mesh)
        return jnp.sum(out ** 2)

    g = jax.grad(loss)(params)
    assert np.isfinite(np.asarray(g["w"])).all()
    # every stage's weights receive gradient
    per_stage = np.abs(np.asarray(g["w"])).sum(axis=(1, 2))
    assert (per_stage > 0).all()
