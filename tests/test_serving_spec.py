"""Speculative decode tick for the continuous-batching engine (ISSUE 7).

The load-bearing contracts:

- greedy spec-engine output is TOKEN-IDENTICAL to the non-spec engine
  and to sequential `utils.generate.generate` — staggered admission,
  slot AND paged layouts, scan_layers + GQA covered (fp32); the int8
  pools must agree spec-vs-non-spec (same quantized entries, same
  reads);
- ONE decode compilation per (layout, dtype, spec_mode, gamma) engine
  — the draft/verify tick must not reintroduce per-request retraces;
- admission reserves gamma EXTRA lane positions (the verify scatters a
  gamma-wide rejected tail past the cursor): the boundary prompt 413s
  on the spec engine and admits on the non-spec one, and the paged
  charge is ceil((bucket + max_new + gamma) / block_size) so
  over-scattered tails never cross into a block the lane doesn't own;
- /stats grows the spec section (mode, gamma, drafted/accepted totals,
  acceptance rate) while the non-spec payload keeps its exact pre-spec
  key set.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from fengshen_tpu.serving import (ContinuousBatchingEngine, EngineConfig,
                                  PromptTooLong)
from fengshen_tpu.utils.generate import generate


def _make(scan=False, kv_heads=None, max_len=64):
    cfg = LlamaConfig(vocab_size=97, hidden_size=32, intermediate_size=64,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=kv_heads,
                      max_position_embeddings=max_len, dtype="float32",
                      scan_layers=scan)
    model = LlamaForCausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 4), jnp.int32))["params"]
    return model, params


@pytest.fixture(scope="module")
def tiny():
    return _make()


def _prompts(lengths, seed=0):
    rng = np.random.RandomState(seed)
    return [rng.randint(3, 96, n).astype(np.int32) for n in lengths]


def _rep_prompts(n, length, seed=0):
    """Repetitive prompts (short-period tiling) — the workload where
    the drafter actually gets proposals accepted."""
    rng = np.random.RandomState(seed)
    return [np.tile(rng.randint(3, 96, 3).astype(np.int32),
                    length)[:length] for _ in range(n)]


def _ref(model, params, prompt, max_new, **kw):
    out = np.asarray(generate(model, params, jnp.asarray(prompt)[None],
                              max_new_tokens=max_new, **kw))
    toks = out[0, len(prompt):].tolist()
    eos = kw.get("eos_token_id")
    if eos is not None and eos in toks:
        toks = toks[:toks.index(eos) + 1]
    return toks


SPEC = dict(spec_mode="prompt_lookup", spec_gamma=4)
PAGED = dict(kv_layout="paged", kv_block_size=16)


# ---- greedy parity (the tentpole contract) ------------------------------

@pytest.mark.parametrize("layout_kw", [{}, PAGED], ids=["slot", "paged"])
def test_spec_greedy_parity_staggered_admission(tiny, layout_kw):
    """Requests admitted at different ticks, spanning both buckets,
    more requests than slots (reclaim mid-stream), decode
    token-identical to sequential generate — lanes at DIFFERENT
    accept counts advance independently."""
    model, params = tiny
    prompts = _prompts((5, 11, 16, 7))
    refs = [_ref(model, params, p, 10) for p in prompts]
    eng = ContinuousBatchingEngine(
        model, params, EngineConfig(num_slots=2, buckets=(8, 16),
                                    max_new_tokens=10, max_queue=16,
                                    **SPEC, **layout_kw))
    r0 = eng.submit(prompts[0])
    r1 = eng.submit(prompts[1])
    for _ in range(3):
        eng.step()
    r2 = eng.submit(prompts[2])
    r3 = eng.submit(prompts[3])
    eng.run_until_idle()
    for req, ref in zip((r0, r1, r2, r3), refs):
        assert req.tokens == ref
        assert req.state == "finished"


@pytest.mark.parametrize("layout_kw", [{}, PAGED], ids=["slot", "paged"])
def test_spec_parity_on_repetitive_prompts_with_acceptance(tiny,
                                                           layout_kw):
    """On the workload the drafter targets, proposals must actually be
    ACCEPTED (else the parity above is vacuous — pure correction-path)
    and the output still token-identical."""
    model, params = tiny
    prompts = _rep_prompts(3, 14, seed=2)
    refs = [_ref(model, params, p, 24) for p in prompts]
    eng = ContinuousBatchingEngine(
        model, params, EngineConfig(num_slots=3, buckets=(16,),
                                    max_new_tokens=24, max_queue=8,
                                    **SPEC, **layout_kw))
    assert eng.generate_all(prompts) == refs
    st = eng.stats()
    assert st["spec_accepted_total"] > 0
    assert 0.0 < st["spec_acceptance_rate"] <= 1.0
    # accepted proposals = fewer verify forwards than committed tokens
    assert st["decode_ticks"] < st["decode_tokens"]


@pytest.mark.parametrize("scan,kv_heads", [(True, 2), (False, 2),
                                           (True, None)])
def test_spec_parity_scan_and_gqa(scan, kv_heads):
    model, params = _make(scan=scan, kv_heads=kv_heads)
    prompts = _prompts((5, 11, 16), seed=1)
    refs = [_ref(model, params, p, 8) for p in prompts]
    for layout_kw in ({}, PAGED):
        eng = ContinuousBatchingEngine(
            model, params, EngineConfig(num_slots=2, buckets=(8, 16),
                                        max_new_tokens=8, max_queue=8,
                                        **SPEC, **layout_kw))
        assert eng.generate_all(prompts) == refs


def test_spec_parity_with_eos(tiny):
    """eos inside an accepted window must cut exactly where the
    non-spec engine cuts (eos included, tail discarded)."""
    model, params = tiny
    prompt = _prompts((9,), seed=3)[0]
    free_run = _ref(model, params, prompt, 12)
    eos = free_run[3]
    ref = _ref(model, params, prompt, 12, eos_token_id=eos)
    eng = ContinuousBatchingEngine(
        model, params, EngineConfig(num_slots=2, buckets=(16,),
                                    max_new_tokens=12, max_queue=4,
                                    eos_token_id=eos, **SPEC))
    req = eng.submit(prompt)
    eng.run_until_idle()
    assert req.tokens == ref
    assert req.tokens[-1] == eos
    assert req.finish_reason == "eos"


@pytest.mark.parametrize("layout_kw", [{}, PAGED], ids=["slot", "paged"])
def test_spec_int8_identical_to_nonspec_engine(tiny, layout_kw):
    """int8 pools: the verify window quantizes the SAME per-(token,
    head) values the plain tick would, so spec output must equal the
    non-spec int8 engine token for token (the fp32 sequential ref is
    compared margin-aware elsewhere — here the contract is
    spec-vs-non-spec equality)."""
    model, params = tiny
    prompts = _prompts((5, 11, 16), seed=11) + _rep_prompts(1, 10,
                                                            seed=4)
    kw = dict(num_slots=2, buckets=(8, 16), max_new_tokens=10,
              max_queue=8, kv_dtype="int8", **layout_kw)
    base = ContinuousBatchingEngine(model, params, EngineConfig(**kw))
    spec = ContinuousBatchingEngine(model, params,
                                    EngineConfig(**SPEC, **kw))
    assert spec.generate_all(prompts) == base.generate_all(prompts)


# ---- compile counts -----------------------------------------------------

@pytest.mark.parametrize("layout_kw,gamma",
                         [({}, 4), (PAGED, 4), ({}, 2),
                          (dict(kv_dtype="int8", **PAGED), 3)],
                         ids=["slot-g4", "paged-g4", "slot-g2",
                              "paged-int8-g3"])
def test_spec_decode_compiles_once_across_reclaim(tiny, layout_kw,
                                                  gamma):
    """One decode program per (layout, dtype, spec_mode, gamma) engine
    for its whole lifetime — staggered admission, reclaim, and both
    prefill buckets (one compile each); assign compiles once."""
    model, params = tiny
    eng = ContinuousBatchingEngine(
        model, params, EngineConfig(num_slots=2, buckets=(8, 16),
                                    max_new_tokens=6, max_queue=16,
                                    spec_mode="prompt_lookup",
                                    spec_gamma=gamma, **layout_kw))
    if not hasattr(eng._decode_jit, "_cache_size"):
        pytest.skip("jit cache introspection unavailable")
    eng.warmup()
    prompts = _prompts((5, 11, 16, 7, 3, 9))
    reqs = [eng.submit(p) for p in prompts[:3]]
    for _ in range(4):
        eng.step()
    reqs += [eng.submit(p) for p in prompts[3:]]
    eng.run_until_idle()
    assert all(r.state == "finished" for r in reqs)
    assert eng._decode_jit._cache_size() == 1
    assert eng._prefill_jit._cache_size() == 2
    assert eng._assign_jit._cache_size() == 1


# ---- admission: the gamma headroom boundary -----------------------------

def test_spec_headroom_boundary_rejects_413(tiny):
    """capacity 64, bucket 60, gamma 4: 64 - 60 - 4 = 0 decode room →
    the spec engine must 413; the SAME prompt admits on the non-spec
    engine (this is exactly the off-by-gamma that would otherwise
    silently clamp the verify window into corrupting the lane)."""
    model, params = tiny
    prompt = _prompts((58,), seed=5)[0]
    eng = ContinuousBatchingEngine(
        model, params, EngineConfig(num_slots=1, buckets=(8, 56, 60),
                                    max_new_tokens=8, max_queue=4,
                                    **SPEC))
    with pytest.raises(PromptTooLong, match="gamma=4"):
        eng.submit(prompt)
    assert eng.stats()["rejected_prompt_too_long"] == 1
    off = ContinuousBatchingEngine(
        model, params, EngineConfig(num_slots=1, buckets=(8, 56, 60),
                                    max_new_tokens=8, max_queue=4))
    req = off.submit(prompt)
    off.run_until_idle()
    assert req.state == "finished"
    # one bucket below the boundary the spec engine admits, with the
    # window clamped to the remaining headroom
    ref = _ref(model, params, _prompts((50,), seed=6)[0], 4)
    req = eng.submit(_prompts((50,), seed=6)[0], max_new_tokens=8)
    eng.run_until_idle()
    assert req.state == "finished"
    assert req.tokens == ref  # clamped to 64 - 56 - 4 = 4 tokens


def test_spec_paged_charge_includes_gamma(tiny):
    """Paged admission must charge ceil((bucket + max_new + gamma) /
    block_size): at bucket 8, max_new 8, gamma 4 → 20 tokens → 2
    blocks of 16, where the gamma-less charge would be 1 — pinned via
    the allocator accounting."""
    model, params = tiny
    eng = ContinuousBatchingEngine(
        model, params, EngineConfig(num_slots=2, buckets=(8,),
                                    max_new_tokens=8, max_queue=8,
                                    kv_layout="paged", kv_block_size=16,
                                    kv_num_blocks=6, **SPEC))
    eng.submit(_prompts((6,), seed=7)[0])
    eng.step()
    assert eng.stats()["kv_blocks_used"] == 2
    off = ContinuousBatchingEngine(
        model, params, EngineConfig(num_slots=2, buckets=(8,),
                                    max_new_tokens=8, max_queue=8,
                                    kv_layout="paged", kv_block_size=16,
                                    kv_num_blocks=6))
    off.submit(_prompts((6,), seed=7)[0])
    off.step()
    assert off.stats()["kv_blocks_used"] == 1


def test_spec_paged_tight_pool_no_cross_lane_corruption(tiny):
    """Adjacent lanes on a pool with EXACTLY the charged blocks: an
    over-scattered rejected tail crossing into a neighbour's block
    would corrupt its committed K/V and break token identity."""
    model, params = tiny
    prompts = _rep_prompts(3, 8, seed=8)
    refs = [_ref(model, params, p, 12) for p in prompts]
    # charge per request: ceil((8 + 12 + 4) / 8) = 3 blocks; pool holds
    # exactly 3 requests' worth (+ null block)
    eng = ContinuousBatchingEngine(
        model, params, EngineConfig(num_slots=3, buckets=(8,),
                                    max_new_tokens=12, max_queue=8,
                                    kv_layout="paged", kv_block_size=8,
                                    kv_num_blocks=10, **SPEC))
    assert eng.generate_all(prompts) == refs
    assert eng.stats()["kv_blocks_used"] == 0


def test_spec_unsatisfiable_paged_footprint_rejected(tiny):
    """The gamma-inclusive footprint can exceed a pool the gamma-less
    one fits into — submit must 413 instead of livelocking the FIFO."""
    model, params = tiny
    # bucket 8 + max_new 8 + gamma 4 = 20 tokens = 2 blocks of 16, but
    # the pool has only 1 allocatable block (fits the gamma-less 16)
    eng = ContinuousBatchingEngine(
        model, params, EngineConfig(num_slots=2, buckets=(8,),
                                    max_new_tokens=8, max_queue=8,
                                    kv_layout="paged", kv_block_size=16,
                                    kv_num_blocks=2, **SPEC))
    with pytest.raises(PromptTooLong, match="KV blocks"):
        eng.submit(_prompts((6,))[0])
    off = ContinuousBatchingEngine(
        model, params, EngineConfig(num_slots=2, buckets=(8,),
                                    max_new_tokens=8, max_queue=8,
                                    kv_layout="paged", kv_block_size=16,
                                    kv_num_blocks=2))
    req = off.submit(_prompts((6,))[0])
    off.run_until_idle()
    assert req.state == "finished"


# ---- config surface -----------------------------------------------------

def test_spec_config_validation(tiny):
    with pytest.raises(ValueError, match="spec_mode"):
        EngineConfig(spec_mode="prompt_lookupp")
    with pytest.raises(ValueError, match="spec_gamma"):
        EngineConfig(spec_mode="prompt_lookup", spec_gamma=0)
    with pytest.raises(ValueError, match="spec_ngram"):
        EngineConfig(spec_mode="prompt_lookup", spec_ngram=0)
    with pytest.raises(ValueError, match="greedy-only"):
        EngineConfig(spec_mode="prompt_lookup", do_sample=True)
    with pytest.raises(ValueError, match="logits controls"):
        EngineConfig(spec_mode="prompt_lookup", repetition_penalty=1.5)
    # a ladder whose smallest bucket fills the lane minus gamma must
    # fail at CONSTRUCTION (no admissible prompt exists)
    model, params = tiny
    with pytest.raises(ValueError, match="gamma=4"):
        ContinuousBatchingEngine(
            model, params, EngineConfig(buckets=(60,), **SPEC))


# ---- /stats + registry --------------------------------------------------

def test_spec_stats_keys_and_nonspec_shape_unchanged(tiny):
    model, params = tiny
    spec = ContinuousBatchingEngine(
        model, params, EngineConfig(num_slots=2, buckets=(16,),
                                    max_new_tokens=16, max_queue=4,
                                    **SPEC))
    spec.generate_all(_rep_prompts(2, 12, seed=9))
    st = spec.stats()
    assert st["spec_mode"] == "prompt_lookup"
    assert st["spec_gamma"] == 4
    assert st["spec_drafted_total"] > 0
    assert 0 <= st["spec_accepted_total"] <= st["spec_drafted_total"]
    assert st["spec_acceptance_rate"] == round(
        st["spec_accepted_total"] / st["spec_drafted_total"], 4)
    from fengshen_tpu.observability import render_prometheus
    text = render_prometheus(spec.metrics.registry)
    assert "fstpu_serving_spec_drafted_total" in text
    assert "fstpu_serving_spec_accepted_total" in text
    assert "fstpu_spec_accepted_ratio" in text
    # the non-spec engine's payload keeps its exact pre-spec key set
    off = ContinuousBatchingEngine(
        model, params, EngineConfig(num_slots=2, buckets=(16,),
                                    max_new_tokens=4, max_queue=4))
    off_keys = set(off.stats())
    assert not any(k.startswith("spec_") for k in off_keys)
    assert set(st) == off_keys | {
        "spec_mode", "spec_gamma", "spec_drafted_total",
        "spec_accepted_total", "spec_acceptance_rate"}


def test_spec_metrics_count_only_delivered_tokens(tiny):
    """A lane finishing mid-window (length cap / eos) discards the
    window tail — decode_tokens must equal the tokens requests
    actually received (minus the prefill token), not the raw committed
    windows, else tokens/s and the bench's committed-per-forward
    headline inflate by up to gamma per request."""
    model, params = tiny
    prompts = _rep_prompts(3, 14, seed=2)   # high-acceptance workload
    eng = ContinuousBatchingEngine(
        model, params, EngineConfig(num_slots=3, buckets=(16,),
                                    max_new_tokens=6, max_queue=8,
                                    **SPEC))
    outs = eng.generate_all(prompts)
    st = eng.stats()
    # the first token of each request comes from prefill, the rest
    # from decode ticks — exactly, despite truncated final windows
    assert st["decode_tokens"] == sum(len(t) - 1 for t in outs)
    assert st["spec_accepted_total"] <= st["decode_tokens"]
    # drafted = gamma per active lane per tick
    assert 0 < st["spec_drafted_total"] <= 4 * st["decode_ticks"] * 3


# ---- AOT integration ----------------------------------------------------

def test_spec_engine_through_aot_cache(tiny, tmp_path):
    """The spec knobs flow into the AOT key (gamma via the verify
    avals, spec_mode via the EngineConfig-repr fingerprint): a spec
    engine warms through the persistent cache, a second engine replays
    it with token parity, and a different gamma coexists as a distinct
    executable."""
    from fengshen_tpu.aot import AotConfig, AotSetup

    model, params = tiny
    prompts = _prompts((5, 11), seed=6)
    refs = [_ref(model, params, p, 6) for p in prompts]

    def build(gamma):
        aot = AotSetup(AotConfig(cache_dir=str(tmp_path)))
        return ContinuousBatchingEngine(
            model, params,
            EngineConfig(num_slots=2, buckets=(8, 16), max_new_tokens=6,
                         max_queue=8, spec_mode="prompt_lookup",
                         spec_gamma=gamma), aot=aot)

    eng = build(4)
    eng.warmup()
    assert eng.generate_all(prompts) == refs
    eng2 = build(4)
    eng2.warmup()                        # warm replay
    assert eng2.generate_all(prompts) == refs
    eng3 = build(2)                      # different gamma, same dir
    eng3.warmup()
    assert eng3.generate_all(prompts) == refs
