"""Smoke tests for the round-2 second example wave: clue_sim,
zen2_finetune, pretrain_randeng_bart (indexed-corpus denoising), deepVAE
pretrain, DAVAE generate demo, tcbert demo."""

import json



import numpy as np
import pytest

pytestmark = pytest.mark.slow  # full-fit/e2e lane: run with -m slow or no -m filter



def _bert_tokenizer_dir(tmp_path):
    from transformers import BertTokenizer
    chars = list("今天天气很好我们去公园散步股市大涨投资者信心回升街头偶遇长安"
                 "颜值美炸汽车财经教育军事中文测试句子新闻标题查询相关不类别")
    vocab = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"] + \
        sorted(set(chars))
    (tmp_path / "vocab.txt").write_text("\n".join(vocab))
    tok = BertTokenizer(str(tmp_path / "vocab.txt"))
    model_dir = tmp_path / "model"
    model_dir.mkdir(exist_ok=True)
    tok.save_pretrained(str(model_dir))
    return tok, model_dir


def _run_args(tmp_path, model_dir, train, extra=()):
    return [
        "--model_path", str(model_dir), "--train_file", str(train),
        "--train_batchsize", "2", "--max_steps", "2",
        "--log_every_n_steps", "1", "--warmup_steps", "1",
        "--default_root_dir", str(tmp_path / "runs"),
        "--save_ckpt_path", str(tmp_path / "ckpt"),
        "--load_ckpt_path", str(tmp_path / "ckpt"),
        "--seed", "1", *extra]


def _losses(tmp_path):
    lines = [json.loads(l) for l in open(tmp_path / "runs" / "metrics.jsonl")]
    return [l["loss"] for l in lines if "loss" in l]


@pytest.mark.parametrize("loss_fn", ["ce", "focal", "lsce"])
def test_clue_sim_e2e(tmp_path, mesh8, loss_fn):
    from fengshen_tpu.examples.clue_sim import finetune_clue_sim
    from fengshen_tpu.models.megatron_bert import MegatronBertConfig
    tok, model_dir = _bert_tokenizer_dir(tmp_path)
    MegatronBertConfig.small_test_config(
        vocab_size=len(tok)).save_pretrained(str(model_dir))
    train = tmp_path / "train.json"
    with open(train, "w") as f:
        for i in range(8):
            f.write(json.dumps({"query": "今天天气很好",
                                "title": "我们去公园散步",
                                "label": i % 3}, ensure_ascii=False) + "\n")
    finetune_clue_sim.main(_run_args(
        tmp_path, model_dir, train,
        ["--max_seq_length", "32", "--loss_function", loss_fn]))
    losses = _losses(tmp_path)
    assert len(losses) == 2 and all(np.isfinite(losses))


def test_zen2_finetune_e2e(tmp_path, mesh8):
    import dataclasses
    import json as _json
    import os

    from fengshen_tpu.examples.zen2_finetune import (
        fengshen_sequence_level_ft_task as task)
    from fengshen_tpu.models.zen2 import Zen2Config
    tok, model_dir = _bert_tokenizer_dir(tmp_path)
    cfg = Zen2Config.small_test_config(vocab_size=len(tok))
    with open(os.path.join(model_dir, "config.json"), "w") as f:
        _json.dump(dataclasses.asdict(cfg), f)
    (model_dir / "ngram.txt").write_text("中文,5\n测试,3\n")
    train = tmp_path / "train.json"
    with open(train, "w") as f:
        for i in range(8):
            f.write(json.dumps({"sentence": "中文测试句子很好",
                                "label": i % 2}, ensure_ascii=False) + "\n")
    task.main(_run_args(tmp_path, model_dir, train,
                        ["--max_seq_length", "32", "--num_labels", "2"]))
    losses = _losses(tmp_path)
    assert len(losses) == 2 and all(np.isfinite(losses))


def test_pretrain_randeng_bart_e2e(tmp_path, mesh8):
    import dataclasses
    import json as _json
    import os

    from fengshen_tpu.data.megatron_dataloader import (
        MMapIndexedDatasetBuilder)
    from fengshen_tpu.examples.pretrain_randeng_bart import pretrain_bart
    from fengshen_tpu.models.bart import BartConfig
    tok, model_dir = _bert_tokenizer_dir(tmp_path)
    BartConfig.small_test_config(vocab_size=len(tok)).save_pretrained(
        str(model_dir))
    rng = np.random.RandomState(0)
    b = MMapIndexedDatasetBuilder(str(tmp_path / "corpus"), dtype=np.int32)
    for _ in range(8):
        for _ in range(3):
            b.add_item(rng.randint(5, len(tok) - 1,
                                   rng.randint(5, 10)).tolist())
        b.end_document()
    b.finalize()
    pretrain_bart.main(_run_args(
        tmp_path, model_dir, tmp_path / "unused.json",
        ["--data_prefix", str(tmp_path / "corpus"),
         "--max_seq_length", "48"]))
    losses = _losses(tmp_path)
    assert len(losses) == 2 and all(np.isfinite(losses))


def test_pretrain_deep_vae_e2e(tmp_path, mesh8, monkeypatch):
    from fengshen_tpu.examples.deepVAE import pretrain_deep_vae
    from fengshen_tpu.models.deepvae import DellaConfig
    tok, model_dir = _bert_tokenizer_dir(tmp_path)
    small = DellaConfig.small_test_config()
    monkeypatch.setattr(pretrain_deep_vae, "DellaConfig", lambda: small)
    train = tmp_path / "train.json"
    with open(train, "w") as f:
        for _ in range(8):
            f.write(json.dumps({"text": "今天天气很好我们去公园散步"},
                               ensure_ascii=False) + "\n")
    pretrain_deep_vae.main(_run_args(
        tmp_path, model_dir, train, ["--max_seq_length", "16"]))
    losses = _losses(tmp_path)
    assert len(losses) == 2 and all(np.isfinite(losses))


def test_davae_generate_demo():
    from fengshen_tpu.examples.DAVAE.generate import main
    out = main(argv=["--max_length", "8"])
    assert out.shape[1] == 8


def test_tcbert_demo(tmp_path):
    from fengshen_tpu.examples.tcbert import example
    from fengshen_tpu.models.megatron_bert import MegatronBertConfig
    from fengshen_tpu.models.tcbert import TCBertPipelines
    tok, _ = _bert_tokenizer_dir(tmp_path)
    cfg = MegatronBertConfig.small_test_config(vocab_size=len(tok))
    pipe = TCBertPipelines(None, tokenizer=tok, config=cfg)
    result = example.main(argv=[], pipeline=pipe)
    assert len(result) == 2 and all(0 <= r < 4 for r in result)


def test_gavae_generate_demo():
    from fengshen_tpu.examples.GAVAE.generate import main
    out = main(argv=["--n", "2", "--gan_steps", "3", "--max_length", "6"])
    assert out.shape == (2, 6)


def test_ppvae_generate_demo():
    from fengshen_tpu.examples.PPVAE.generate import main
    out = main(argv=["--n", "2", "--plugin_steps", "5",
                     "--max_length", "6"])
    assert out.shape == (2, 6)


def test_longformer_finetune_e2e(tmp_path, mesh8):
    import dataclasses
    import json as _json
    import os

    from fengshen_tpu.examples.longformer import finetune_longformer
    from fengshen_tpu.models.longformer.modeling_longformer import (
        LongformerConfig)
    tok, model_dir = _bert_tokenizer_dir(tmp_path)
    cfg = LongformerConfig.small_test_config(vocab_size=len(tok),
                                             dtype="float32")
    with open(os.path.join(model_dir, "config.json"), "w") as f:
        _json.dump(dataclasses.asdict(cfg), f)
    train = tmp_path / "train.json"
    with open(train, "w") as f:
        for i in range(8):
            f.write(json.dumps({"text": "今天天气很好我们去公园散步" * 2,
                                "label": i % 2}, ensure_ascii=False) + "\n")
    finetune_longformer.main(_run_args(
        tmp_path, model_dir, train,
        ["--max_seq_length", "48", "--num_labels", "2"]))
    losses = _losses(tmp_path)
    assert len(losses) == 2 and all(np.isfinite(losses))


def test_sd_txt2img_demo(tmp_path):
    from fengshen_tpu.examples.stable_diffusion_chinese.demo import main
    out = main(argv=["--image_size", "32", "--num_steps", "3",
                     "--out", str(tmp_path / "sd_demo.png")])
    assert out.shape[0] == 1 and out.shape[1] == 32
    assert np.isfinite(out).all() and 0 <= out.min() and out.max() <= 1


def test_randeng_reasoning_demo():
    from fengshen_tpu.examples.randeng_reasoning.generate import main
    out = main(argv=["--mode", "abduction", "--max_out_seq", "16"])
    assert len(out) == 1


def test_disco_guided_diffusion_demo():
    from fengshen_tpu.examples.disco_project.guided_diffusion_demo import (
        main)
    out = main(argv=["--image_size", "32", "--num_steps", "2"])
    assert out.shape[1] == 32 and np.isfinite(out).all()


def test_uniex_fit_and_predict(tmp_path, mesh8):
    """UniEX now trains (fit + predict round trip, completing the
    ubert/unimc/uniex pipeline trio)."""
    import argparse

    from fengshen_tpu.models.megatron_bert import MegatronBertConfig
    from fengshen_tpu.models.uniex import UniEXPipelines
    tok, _ = _bert_tokenizer_dir(tmp_path)
    cfg = MegatronBertConfig.small_test_config(vocab_size=len(tok))
    parser = UniEXPipelines.pipelines_args(argparse.ArgumentParser())
    args = parser.parse_args([
        "--max_length", "48", "--train_batchsize", "2", "--max_steps", "2",
        "--log_every_n_steps", "1", "--warmup_steps", "1",
        "--default_root_dir", str(tmp_path / "runs")])
    pipe = UniEXPipelines(args, tokenizer=tok, config=cfg)
    train = [{"text": "今天天气很好我们去公园散步",
              "choices": [
                  {"entity_type": "天气",
                   "entity_list": [{"entity_idx": [[0, 3]]}]},
                  {"entity_type": "地名",
                   "entity_list": [{"entity_idx": [[9, 10]]}]}]}] * 4
    pipe.fit(train)
    out = pipe.predict([{"text": "今天天气很好",
                         "choices": [{"entity_type": "天气"}]}])
    assert len(out) == 1 and "entity_list" in out[0]


def test_zen1_token_level_e2e(tmp_path, mesh8):
    import dataclasses
    import json as _json
    import os

    from fengshen_tpu.examples.zen1_finetune import (
        fengshen_token_level_ft_task as task)
    from fengshen_tpu.models.zen import ZenConfig
    tok, model_dir = _bert_tokenizer_dir(tmp_path)
    cfg = ZenConfig.small_test_config(vocab_size=len(tok))
    with open(os.path.join(model_dir, "config.json"), "w") as f:
        _json.dump(dataclasses.asdict(cfg), f)
    (model_dir / "ngram.txt").write_text("中文,5\n测试,3\n")
    data_dir = tmp_path / "data"
    data_dir.mkdir()
    conll = "\n".join(["中 B-LOC", "文 I-LOC", "测 O", "试 O", "",
                       "句 B-LOC", "子 I-LOC", "很 O", "好 O", ""])
    (data_dir / "train.char.bio").write_text(conll * 4)
    task.main(_run_args(
        tmp_path, model_dir, tmp_path / "unused.json",
        ["--max_seq_length", "32", "--data_dir", str(data_dir)]))
    losses = _losses(tmp_path)
    assert len(losses) == 2 and all(np.isfinite(losses))


def test_zen2_token_level_e2e(tmp_path, mesh8):
    """ner_zen2_* shells drive THIS module — zen2 tower (relative
    attention) + freq-weighted ngram matrix on the CoNLL pipeline."""
    import dataclasses
    import json as _json
    import os

    from fengshen_tpu.examples.zen2_finetune import (
        fengshen_token_level_ft_task as task)
    from fengshen_tpu.models.zen2 import Zen2Config
    tok, model_dir = _bert_tokenizer_dir(tmp_path)
    cfg = Zen2Config.small_test_config(vocab_size=len(tok))
    with open(os.path.join(model_dir, "config.json"), "w") as f:
        _json.dump(dataclasses.asdict(cfg), f)
    (model_dir / "ngram.txt").write_text("中文,5\n测试,3\n")
    data_dir = tmp_path / "data"
    data_dir.mkdir()
    conll = "\n".join(["中 B-LOC", "文 I-LOC", "测 O", "试 O", "",
                       "句 B-LOC", "子 I-LOC", "很 O", "好 O", ""])
    (data_dir / "train.char.bio").write_text(conll * 4)
    task.main(_run_args(
        tmp_path, model_dir, tmp_path / "unused.json",
        ["--max_seq_length", "32", "--data_dir", str(data_dir)]))
    losses = _losses(tmp_path)
    assert len(losses) == 2 and all(np.isfinite(losses))


@pytest.mark.slow
def test_stable_diffusion_EN_demo_passes_bilingual_checkpoint(tmp_path,
                                                              monkeypatch):
    """The _EN demo must inject the bilingual checkpoint path and an
    English default prompt (it was a bare alias of the zh main before
    round 4)."""
    monkeypatch.chdir(tmp_path)
    from fengshen_tpu.examples.stable_diffusion_chinese_EN import demo

    captured = {}

    def fake_zh_main(argv=None, **kwargs):
        captured["argv"] = list(argv)
        return None

    monkeypatch.setattr(
        "fengshen_tpu.examples.stable_diffusion_chinese.demo.main",
        fake_zh_main)
    demo.main([])
    argv = captured["argv"]
    i = argv.index("--model_path")
    assert "Chinese-EN" in argv[i + 1]
    assert "--prompt" in argv
    # explicit flags win over the injected defaults
    demo.main(["--model_path", "/my/ckpt", "--prompt", "hi"])
    assert captured["argv"].count("--model_path") == 1
    assert "/my/ckpt" in captured["argv"]


@pytest.mark.slow
def test_stable_diffusion_EN_demo_runs_small(tmp_path):
    """End-to-end sampling at demo scale through the EN wrapper."""
    import numpy as np

    from fengshen_tpu.examples.stable_diffusion_chinese_EN import demo

    imgs = demo.main(["--model_path", "", "--image_size", "32",
                      "--num_steps", "2",
                      "--out", str(tmp_path / "out.png")])
    assert np.asarray(imgs).shape[-1] == 3
