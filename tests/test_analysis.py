"""fslint (fengshen_tpu.analysis) — rule fixtures, engine mechanics,
baseline workflow, CLI contract, and the fast-lane whole-package gate.

This file supersedes the old regex lint in test_lint_excepts.py: the
AST `blanket-except` rule gives the same guarantee (no silent blanket
handlers anywhere in fengshen_tpu/) without string/comment false
positives, and the whole-package test below enforces it along with the
five SPMD rules.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from fengshen_tpu.analysis import (all_rule_ids, build_index, check_file,
                                   check_paths, default_project_root,
                                   make_rules)
from fengshen_tpu.analysis import baseline as baseline_mod
from fengshen_tpu.analysis.cli import _changed_py_files
from fengshen_tpu.analysis.cli import main as fslint_main

REPO = default_project_root()
PKG = os.path.join(REPO, "fengshen_tpu")
FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "analysis_fixtures")

RULE_IDS = ("api-surface-parity", "blanket-except", "blocking-transfer",
            "blocking-under-lock", "donated-buffer-use",
            "host-divergence", "lock-order", "metric-contract",
            "metrics-in-traced-code", "nondet-iteration",
            "partition-spec-axes", "resource-lifecycle",
            "retrace-hazard", "unguarded-shared-state")

CONCURRENCY_RULE_IDS = ("blocking-under-lock", "lock-order",
                        "unguarded-shared-state")

DATAFLOW_RULE_IDS = ("api-surface-parity", "donated-buffer-use",
                     "metric-contract", "resource-lifecycle")


def _fixture(rule_id: str, kind: str) -> str:
    path = os.path.join(FIXTURES,
                        f"{rule_id.replace('-', '_')}_{kind}.py")
    assert os.path.exists(path), f"missing fixture {path}"
    return path


def test_registry_has_the_shipped_rules():
    assert set(RULE_IDS) <= set(all_rule_ids())


@pytest.mark.parametrize("rule_id", RULE_IDS)
def test_rule_fires_on_bad_fixture(rule_id):
    findings = check_file(_fixture(rule_id, "bad"), make_rules(), REPO)
    hits = [f for f in findings if f.rule == rule_id]
    assert hits, f"{rule_id} found nothing in its known-bad fixture"
    for f in hits:
        assert f.line > 0 and f.hint and f.code


@pytest.mark.parametrize("rule_id", RULE_IDS)
def test_rule_quiet_on_clean_fixture(rule_id):
    findings = check_file(_fixture(rule_id, "clean"), make_rules(), REPO)
    hits = [f for f in findings if f.rule == rule_id]
    assert not hits, (
        f"{rule_id} false-positives on idiomatic clean code:\n"
        + "\n".join(f.render() for f in hits))


def test_clean_fixtures_are_fully_clean():
    """No rule — not just the one under test — fires on a clean
    fixture: cross-rule noise in the clean set means a precision bug."""
    for rule_id in RULE_IDS:
        findings = check_file(_fixture(rule_id, "clean"), make_rules(),
                              REPO)
        assert not findings, "\n".join(f.render() for f in findings)


def test_package_is_clean_under_shipped_baseline():
    """The fast-lane gate: the full analyzer over fengshen_tpu/ must
    report zero non-baselined findings on the merged tree."""
    findings = check_paths([PKG], make_rules(), REPO)
    entries = baseline_mod.load_baseline(
        baseline_mod.default_baseline_path(REPO))
    new, _, stale = baseline_mod.split_by_baseline(findings, entries)
    assert not new, (
        "fslint found non-baselined findings — fix them, suppress with "
        "a justified `# fslint: disable=<rule>`, or (legacy only) "
        "baseline them:\n" + "\n".join(f.render() for f in new))
    assert not stale, (
        "stale baseline entries (the finding no longer fires) — run "
        f"--write-baseline or delete them: {stale}")


def test_sharding_rules_are_clean():
    """The declarative-sharding gate (docs/sharding.md): every
    `*PARAM_LOGICAL_AXES` / `*LOGICAL_AXIS_RULES` table in the package
    validates against the vocabularies — with NO baseline escape hatch
    (a typo'd logical or mesh axis silently replicates a dimension, so
    these tables must stay clean, not baselined)."""
    from fengshen_tpu.analysis.rules.partition_spec_axes import (
        logical_axes, mesh_axes)
    # the gate is only meaningful if both vocabularies parse
    assert logical_axes(REPO), "LOGICAL_AXES not parseable from " \
        "fengshen_tpu/sharding/axes.py"
    assert mesh_axes(REPO), "mesh axes not parseable from " \
        "fengshen_tpu/parallel/mesh.py"
    findings = [f for f in check_paths([PKG], make_rules(), REPO)
                if f.rule == "partition-spec-axes"]
    assert not findings, "\n".join(f.render() for f in findings)


def _write(tmp_path, name, body):
    path = tmp_path / name
    path.write_text(textwrap.dedent(body), encoding="utf-8")
    return str(path)


def test_per_line_suppression(tmp_path):
    bad = """
    def f(fn):
        try:
            fn()
        except Exception:
            pass
    """
    path = _write(tmp_path, "mod.py", bad)
    assert [f.rule for f in check_file(path, make_rules(), REPO)] == \
        ["blanket-except"]

    suppressed = bad.replace(
        "except Exception:",
        "except Exception:  # fslint: disable=blanket-except")
    path = _write(tmp_path, "mod2.py", suppressed)
    assert not check_file(path, make_rules(), REPO)

    # bare `disable` silences every rule on the line
    suppressed_all = bad.replace("except Exception:",
                                 "except Exception:  # fslint: disable")
    path = _write(tmp_path, "mod3.py", suppressed_all)
    assert not check_file(path, make_rules(), REPO)

    # a different rule id does NOT silence it
    wrong = bad.replace(
        "except Exception:",
        "except Exception:  # fslint: disable=host-divergence")
    path = _write(tmp_path, "mod4.py", wrong)
    assert [f.rule for f in check_file(path, make_rules(), REPO)] == \
        ["blanket-except"]


def test_baseline_pins_by_code_not_line(tmp_path):
    src = """
    def f(fn):
        try:
            fn()
        except Exception:
            pass
    """
    path = _write(tmp_path, "legacy.py", src)
    findings = check_file(path, make_rules(), REPO)
    assert len(findings) == 1

    bl = tmp_path / "baseline.json"
    baseline_mod.write_baseline(str(bl), findings)
    entries = baseline_mod.load_baseline(str(bl))
    assert entries and "justification" in entries[0]

    # unrelated lines added ABOVE: line number moves, baseline holds
    shifted = "import os  # noqa: F401\nimport sys  # noqa: F401\n" + \
        textwrap.dedent(src)
    (tmp_path / "legacy.py").write_text(shifted, encoding="utf-8")
    findings2 = check_file(str(tmp_path / "legacy.py"), make_rules(),
                           REPO)
    new, baselined, stale = baseline_mod.split_by_baseline(findings2,
                                                           entries)
    assert not new and len(baselined) == 1 and not stale

    # the flagged LINE itself changes: finding resurfaces, entry stale
    edited = textwrap.dedent(src).replace("except Exception:",
                                          "except BaseException:")
    (tmp_path / "legacy.py").write_text(edited, encoding="utf-8")
    findings3 = check_file(str(tmp_path / "legacy.py"), make_rules(),
                           REPO)
    new, baselined, stale = baseline_mod.split_by_baseline(findings3,
                                                           entries)
    assert len(new) == 1 and not baselined and len(stale) == 1


def test_json_output_is_sorted_and_stable(tmp_path, capsys):
    _write(tmp_path, "b.py", """
    import random, jax

    @jax.jit
    def f(x):
        return x + random.random()

    def g(fn):
        try:
            fn()
        except Exception:
            pass
    """)
    _write(tmp_path, "a.py", """
    def h(fn):
        try:
            fn()
        except:
            pass
    """)
    argv = [str(tmp_path), "--json", "--no-baseline"]
    assert fslint_main(argv) == 1
    out1 = capsys.readouterr().out
    assert fslint_main(argv) == 1
    out2 = capsys.readouterr().out
    assert out1 == out2, "--json output is not deterministic"

    report = json.loads(out1)
    keys = [(f["path"], f["line"], f["col"], f["rule"])
            for f in report["findings"]]
    assert keys == sorted(keys)
    assert [f["rule"] for f in report["findings"]] == \
        ["blanket-except", "host-divergence", "blanket-except"]


def test_cli_select_ignore_and_unknown_rule(tmp_path, capsys):
    path = _write(tmp_path, "m.py", """
    def f(fn):
        try:
            fn()
        except Exception:
            pass
    """)
    assert fslint_main([path, "--no-baseline",
                        "--select", "blanket-except"]) == 1
    capsys.readouterr()
    assert fslint_main([path, "--no-baseline",
                        "--ignore", "blanket-except"]) == 0
    capsys.readouterr()
    assert fslint_main([path, "--select", "no-such-rule"]) == 2
    assert "unknown rule" in capsys.readouterr().err


def test_cli_write_baseline_roundtrip(tmp_path, capsys):
    path = _write(tmp_path, "m.py", """
    def f(fn):
        try:
            fn()
        except Exception:
            pass
    """)
    bl = str(tmp_path / "bl.json")
    assert fslint_main([path, "--baseline", bl,
                        "--write-baseline"]) == 0
    capsys.readouterr()
    # baselined now: exit 0; byte-stable on rewrite
    assert fslint_main([path, "--baseline", bl]) == 0
    first = open(bl, encoding="utf-8").read()
    assert fslint_main([path, "--baseline", bl,
                        "--write-baseline"]) == 0
    assert open(bl, encoding="utf-8").read() == first


def test_partial_write_baseline_keeps_other_rules(tmp_path, capsys):
    """--write-baseline with --select must not delete baseline entries
    for rules (or paths) it never re-checked."""
    path = _write(tmp_path, "m.py", """
    import random, jax

    @jax.jit
    def f(x):
        return x + random.random()

    def g(fn):
        try:
            fn()
        except Exception:
            pass
    """)
    bl = str(tmp_path / "bl.json")
    assert fslint_main([path, "--baseline", bl,
                        "--write-baseline"]) == 0
    capsys.readouterr()
    entries = baseline_mod.load_baseline(bl)
    assert sorted(e["rule"] for e in entries) == \
        ["blanket-except", "host-divergence"]

    # rewrite only the blanket-except view: host-divergence must survive
    assert fslint_main([path, "--baseline", bl, "--select",
                        "blanket-except", "--write-baseline"]) == 0
    capsys.readouterr()
    entries = baseline_mod.load_baseline(bl)
    assert sorted(e["rule"] for e in entries) == \
        ["blanket-except", "host-divergence"]
    # and the full gate still passes against the merged baseline
    assert fslint_main([path, "--baseline", bl]) == 0


def test_blocking_transfer_taint_skips_static_shape_math(tmp_path):
    """Trace-time-static host math in traced code must NOT fire: config
    attributes, `.shape` metadata, mesh sizes, annotated scalars."""
    path = _write(tmp_path, "shapes.py", """
    import math
    import jax

    class Cfg:
        hidden_size = 512


    def run(cfg, n_experts: int, mesh):
        @jax.jit
        def step(x):
            b, s, h = x.shape
            tokens = b * s
            capacity = max(1, int(math.ceil(tokens / n_experts)))
            inter = int(2 * 4 * cfg.hidden_size / 3)
            width = int(mesh.shape["tensor"])
            loss = (x ** 2).mean()
            return loss * capacity * inter * width, float(loss)

        return step
    """)
    findings = check_file(path, make_rules(), REPO)
    assert [f.rule for f in findings] == ["blocking-transfer"]
    assert "float" in findings[0].message


def test_nonexistent_path_fails_loudly(tmp_path, capsys):
    """A typo'd path must not lint nothing and report 'clean' — that
    would make the CI gate vacuous."""
    missing = str(tmp_path / "no_such_dir")
    with pytest.raises(FileNotFoundError):
        check_paths([missing], make_rules(), REPO)
    assert fslint_main([missing, "--no-baseline"]) == 2
    assert "no such file" in capsys.readouterr().err


def test_host_divergence_environ_as_call_argument(tmp_path):
    path = _write(tmp_path, "env.py", """
    import os
    import jax

    @jax.jit
    def f(x):
        env = dict(os.environ)
        return x * len(env)
    """)
    findings = check_file(path, make_rules(), REPO)
    assert [f.rule for f in findings] == ["host-divergence"]


def test_retrace_hazard_ignores_local_shadowing(tmp_path):
    path = _write(tmp_path, "shadow.py", """
    import jax
    import jax.numpy as jnp

    MASK = jnp.zeros((4,))

    @jax.jit
    def f(x):
        MASK = x * 2  # local rebinding, not a closure
        return MASK

    @jax.jit
    def g(x):
        return x + MASK  # the real closure still fires
    """)
    findings = check_file(path, make_rules(), REPO)
    assert len(findings) == 1
    assert findings[0].rule == "retrace-hazard" and "g" in \
        findings[0].message


def test_blocking_transfer_taints_loop_targets(tmp_path):
    path = _write(tmp_path, "loop.py", """
    import jax

    @jax.jit
    def f(xs):
        total = 0.0
        for x in xs:
            total += x.item()
        return total
    """)
    findings = check_file(path, make_rules(), REPO)
    assert [f.rule for f in findings] == ["blocking-transfer"]


def test_parse_error_is_a_finding(tmp_path):
    path = _write(tmp_path, "broken.py", "def f(:\n")
    findings = check_file(path, make_rules(), REPO)
    assert [f.rule for f in findings] == ["parse-error"]


def test_traced_context_spans_local_call_chains(tmp_path):
    """A hazard two calls below a jit entry point is still caught."""
    path = _write(tmp_path, "chain.py", """
    import time
    import jax

    def leaf(x):
        return x * time.time()

    def mid(x):
        return leaf(x) + 1

    def run(xs):
        return jax.jit(mid)(xs)
    """)
    findings = check_file(path, make_rules(), REPO)
    assert [f.rule for f in findings] == ["host-divergence"]


def test_aot_cache_internals_are_clean():
    """Regression fixture for the AOT subsystem (docs/aot_cache.md):
    the cached_compile idiom — metric bumps, pickle/file I/O, and host
    syncs strictly OUTSIDE traced code — must not trip
    `metrics-in-traced-code` or `blocking-transfer` (nor any other
    rule), here or in the real modules. If this starts firing, either
    the cache grew a traced-context side effect (a real bug) or a rule
    lost precision."""
    fixture = os.path.join(FIXTURES, "aot_cache_clean.py")
    findings = check_file(fixture, make_rules(), REPO)
    assert not findings, "\n".join(f.render() for f in findings)

    aot_pkg = os.path.join(PKG, "aot")
    findings = check_paths([aot_pkg], make_rules(), REPO)
    hits = [f for f in findings
            if f.rule in ("metrics-in-traced-code", "blocking-transfer")]
    assert not hits, "\n".join(f.render() for f in hits)


def test_offload_policy_internals_are_clean():
    """Regression fixture for the memory-placement subsystem (ISSUE 9,
    docs/offload.md): the capability probe runs OUTSIDE traced code by
    construction (its tiny transfer + block_until_ready are host-side),
    the placement math is pure host integers, and the gauges are set
    between jit boundaries — none of `host-divergence`,
    `blocking-transfer`, or `metrics-in-traced-code` may fire on the
    fixture or on the real modules (trainer/memory.py and the
    train_state/param_streaming wiring). A hit means a probe or gauge
    leaked into a traced program (a real SPMD hazard) or a rule lost
    precision."""
    fixture = os.path.join(FIXTURES, "offload_policy_clean.py")
    findings = check_file(fixture, make_rules(), REPO)
    assert not findings, "\n".join(f.render() for f in findings)

    paths = [os.path.join(PKG, "trainer", "memory.py"),
             os.path.join(PKG, "trainer", "train_state.py"),
             os.path.join(PKG, "trainer", "param_streaming.py")]
    findings = check_paths(paths, make_rules(), REPO)
    hits = [f for f in findings
            if f.rule in ("metrics-in-traced-code", "blocking-transfer",
                          "host-divergence")]
    assert not hits, "\n".join(f.render() for f in hits)


def test_spec_decode_internals_are_clean():
    """Regression fixture for the speculative decode tick (ISSUE 7):
    the drafter + verify + accept/commit stay ONE pure traced program
    (the n-gram matcher is a tempting place to leak an `.item()` or a
    metrics bump), host syncs and counters strictly between jit
    boundaries — neither `metrics-in-traced-code`,
    `blocking-transfer` nor `host-divergence` may fire on the fixture
    or on the real modules (the serving package and utils/generate.py,
    which owns the shared drafter/accept helpers)."""
    fixture = os.path.join(FIXTURES, "spec_decode_clean.py")
    findings = check_file(fixture, make_rules(), REPO)
    assert not findings, "\n".join(f.render() for f in findings)

    paths = [os.path.join(PKG, "serving"),
             os.path.join(PKG, "utils", "generate.py")]
    findings = check_paths(paths, make_rules(), REPO)
    hits = [f for f in findings
            if f.rule in ("metrics-in-traced-code", "blocking-transfer",
                          "host-divergence")]
    assert not hits, "\n".join(f.render() for f in hits)


def test_flight_recorder_internals_are_clean():
    """Regression fixture for the request-timeline / flight-recorder
    tier (ISSUE 8): lifecycle timestamps, the event ring, phase
    histograms, and the post-mortem dump are HOST-side bookkeeping
    between jit boundaries — `metrics-in-traced-code`,
    `blocking-transfer` and `host-divergence` must all stay silent on
    the fixture and on the real modules (the observability package,
    the serving package whose engine appends the timeline events, and
    the api layer's debug endpoints). A hit means a clock/counter/sync
    leaked into a traced program (a real hazard: timelines must never
    add traced work) or a rule lost precision."""
    fixture = os.path.join(FIXTURES, "flight_recorder_clean.py")
    findings = check_file(fixture, make_rules(), REPO)
    assert not findings, "\n".join(f.render() for f in findings)

    paths = [os.path.join(PKG, "observability"),
             os.path.join(PKG, "serving"),
             os.path.join(PKG, "api")]
    findings = check_paths(paths, make_rules(), REPO)
    hits = [f for f in findings
            if f.rule in ("metrics-in-traced-code", "blocking-transfer",
                          "host-divergence")]
    assert not hits, "\n".join(f.render() for f in hits)


def test_fleet_router_internals_are_clean():
    """Regression fixture for the fleet router (ISSUE 10,
    docs/fleet.md): the router is pure host-side stdlib — clocks,
    seeded backoff jitter, breaker counters, fleet metrics — and must
    STAY outside every traced program. Neither `host-divergence`,
    `blocking-transfer` nor `metrics-in-traced-code` may fire on the
    fixture or on the real `fengshen_tpu/fleet/` package. A hit means
    routing state leaked into a traced program (a real SPMD hazard) or
    a rule lost precision."""
    fixture = os.path.join(FIXTURES, "fleet_router_clean.py")
    findings = check_file(fixture, make_rules(), REPO)
    assert not findings, "\n".join(f.render() for f in findings)

    fleet_pkg = os.path.join(PKG, "fleet")
    findings = check_paths([fleet_pkg], make_rules(), REPO)
    hits = [f for f in findings
            if f.rule in ("metrics-in-traced-code", "blocking-transfer",
                          "host-divergence")]
    assert not hits, "\n".join(f.render() for f in hits)


def test_disagg_internals_are_clean():
    """Regression fixture for the prefill/decode disaggregation tier
    (ISSUE 13, docs/disaggregation.md): lane export/adopt is EAGER
    host-orchestrated array work between jit boundaries (zero new
    compiled programs), the transfer plane is blocking stdlib HTTP on
    the coordinator thread, and the `fstpu_disagg_*` counters mutate
    only around those host steps — neither `host-divergence`,
    `blocking-transfer` nor `metrics-in-traced-code` may fire on the
    fixture or on the real disagg package + `serving/handoff.py`. A
    hit means a lane gather/scatter or a KV push leaked into a traced
    program (a real hazard: compile-count drift or a device-blocking
    decode tick) or a rule lost precision."""
    fixture = os.path.join(FIXTURES, "disagg_clean.py")
    findings = check_file(fixture, make_rules(), REPO)
    assert not findings, "\n".join(f.render() for f in findings)

    paths = [os.path.join(PKG, "disagg"),
             os.path.join(PKG, "serving", "handoff.py")]
    findings = check_paths(paths, make_rules(), REPO)
    hits = [f for f in findings
            if f.rule in ("metrics-in-traced-code", "blocking-transfer",
                          "host-divergence")]
    assert not hits, "\n".join(f.render() for f in hits)


def test_evac_internals_are_clean():
    """Regression fixture for the preemption-tolerance tier (ISSUE 16,
    docs/fault_tolerance.md "Preemption runbook"): the commit journal
    appends on the scheduler thread under a plain lock, the drain-time
    lane export is an EAGER host-side gather (a drain adds zero
    compiled programs), the evacuation push is blocking HTTP on the
    drain thread, and the resume prefill is host-side token concat
    riding the SAME bucketed prefill program — neither
    `host-divergence`, `blocking-transfer` nor
    `metrics-in-traced-code` may fire on the fixture or on the real
    evacuation/resume modules (the disagg package that owns
    `evacuate_all`, `serving/handoff.py`'s detach-as-evacuated, and
    the engine that owns the journal + resume admission). A hit means
    a journal append, an evacuation push, or a resume concat leaked
    into a traced program (a real hazard: per-token journal work must
    cost dict-append, and a recovery must never retrace) or a rule
    lost precision."""
    fixture = os.path.join(FIXTURES, "evac_clean.py")
    findings = check_file(fixture, make_rules(), REPO)
    assert not findings, "\n".join(f.render() for f in findings)

    paths = [os.path.join(PKG, "disagg"),
             os.path.join(PKG, "serving", "handoff.py"),
             os.path.join(PKG, "serving", "engine.py")]
    findings = check_paths(paths, make_rules(), REPO)
    hits = [f for f in findings
            if f.rule in ("metrics-in-traced-code", "blocking-transfer",
                          "host-divergence")]
    assert not hits, "\n".join(f.render() for f in hits)


def test_streaming_internals_are_clean():
    """Regression fixture for the streaming tier (ISSUE 20,
    docs/streaming.md): the per-lane key ring splits IN-GRAPH inside
    the jitted tick (reproducibility is a property of the carried
    keys, not of host randomness), the commit-then-publish stream sync
    is plain-lock host work on the scheduler thread, and SSE framing +
    the blocking socket write + the TTFB observation live on the
    reader's delivery thread — neither `metrics-in-traced-code`,
    `blocking-transfer` nor `host-divergence` may fire on the fixture
    or on the real modules (the streaming package, the serving engine
    that owns the ring + `_sync_stream`, and the api/fleet layers that
    frame and proxy the wire). A hit means a publish, a socket write,
    or a counter leaked into a traced program (a real hazard:
    streaming must add ZERO per-token compiled work) or a rule lost
    precision.

    The same gate pins api-surface parity for the new wire: the
    `/stream` route must be visible to `extract_routes` on BOTH
    surfaces of api/main.py — fastapi decorator and stdlib dispatcher
    — so `api-surface-parity` keeps diffing it (a BinOp-concatenated
    path would silently drop out of the extractor and the rule would
    stop guarding the route)."""
    fixture = os.path.join(FIXTURES, "streaming_clean.py")
    findings = check_file(fixture, make_rules(), REPO)
    assert not findings, "\n".join(f.render() for f in findings)

    paths = [os.path.join(PKG, "streaming"),
             os.path.join(PKG, "serving"),
             os.path.join(PKG, "api"),
             os.path.join(PKG, "fleet")]
    findings = check_paths(paths, make_rules(), REPO)
    hits = [f for f in findings
            if f.rule in ("metrics-in-traced-code", "blocking-transfer",
                          "host-divergence")]
    assert not hits, "\n".join(f.render() for f in hits)

    # the SSE route is on both surfaces of the dual-stack api module,
    # in extractor-visible form, and the parity rule stays green
    import ast as _ast
    from fengshen_tpu.analysis.dataflow import extract_routes
    api_main = os.path.join(PKG, "api", "main.py")
    with open(api_main, encoding="utf-8") as fp:
        tree = _ast.parse(fp.read())
    routes = extract_routes(tree)
    stream_surfaces = {s for (s, method, path, _l, _c) in routes
                       if method == "POST" and path.endswith("*")}
    assert stream_surfaces == {"fastapi", "stdlib"}, routes
    parity = check_paths([os.path.join(PKG, "api")],
                         make_rules(select=["api-surface-parity"]),
                         REPO)
    assert not parity, "\n".join(f.render() for f in parity)


def test_trace_context_internals_are_clean():
    """Regression fixture for the distributed-tracing tier (ISSUE 11,
    docs/observability.md "Distributed tracing"): trace/span ids come
    from a host-side `random.Random`, span stamps from host clocks,
    and the ledger/assembly are plain-dict work on the router and
    scheduler threads — neither `host-divergence`,
    `blocking-transfer` nor `metrics-in-traced-code` may fire on the
    fixture or on the real modules (the observability package that
    owns the ledger, the fleet package that records the spans, and
    the serving+api layers the context flows through). A hit means a
    trace id mint / wall anchor / counter leaked into a traced
    program (a real hazard: tracing must add ZERO per-token work) or
    a rule lost precision."""
    fixture = os.path.join(FIXTURES, "trace_context_clean.py")
    findings = check_file(fixture, make_rules(), REPO)
    assert not findings, "\n".join(f.render() for f in findings)

    paths = [os.path.join(PKG, "observability"),
             os.path.join(PKG, "fleet"),
             os.path.join(PKG, "serving"),
             os.path.join(PKG, "api")]
    findings = check_paths(paths, make_rules(), REPO)
    hits = [f for f in findings
            if f.rule in ("metrics-in-traced-code", "blocking-transfer",
                          "host-divergence")]
    assert not hits, "\n".join(f.render() for f in hits)


def test_paged_cache_internals_are_clean():
    """Regression fixture for the paged KV cache (ISSUE 6): block
    free-list math stays host-side, the traced gather/scatter decode
    stays pure — neither `metrics-in-traced-code`, `blocking-transfer`
    nor `host-divergence` may fire on the fixture or on the real
    serving package. A hit means either the allocator leaked into
    traced code (a real hazard: a python list mutated under trace is a
    silent retrace/divergence bug) or a rule lost precision."""
    fixture = os.path.join(FIXTURES, "paged_cache_clean.py")
    findings = check_file(fixture, make_rules(), REPO)
    assert not findings, "\n".join(f.render() for f in findings)

    serving_pkg = os.path.join(PKG, "serving")
    findings = check_paths([serving_pkg], make_rules(), REPO)
    hits = [f for f in findings
            if f.rule in ("metrics-in-traced-code", "blocking-transfer",
                          "host-divergence")]
    assert not hits, "\n".join(f.render() for f in hits)


def test_pallas_internals_are_clean():
    """Regression fixture for the kernel dispatch seam (docs/
    kernels.md): the capability probe is cached host-side and the
    pallas-vs-xla decision is a compile-time constant — NOT a value
    re-read inside a traced function (the retrace hazard the seam
    exists to avoid) — and the dispatch gauge / loud startup line stay
    between jit boundaries. Neither `metrics-in-traced-code`,
    `blocking-transfer` nor `host-divergence` may fire on the fixture
    or on the real kernel layer + its two biggest consumers (the llama
    decode path and the serving engine)."""
    fixture = os.path.join(FIXTURES, "pallas_kernels_clean.py")
    findings = check_file(fixture, make_rules(), REPO)
    assert not findings, "\n".join(f.render() for f in findings)

    kernel_layer = [
        os.path.join(PKG, "ops", "pallas"),
        os.path.join(PKG, "models", "llama", "modeling_llama.py"),
        os.path.join(PKG, "serving", "engine.py"),
    ]
    findings = check_paths(kernel_layer, make_rules(), REPO)
    hits = [f for f in findings
            if f.rule in ("metrics-in-traced-code", "blocking-transfer",
                          "host-divergence")]
    assert not hits, "\n".join(f.render() for f in hits)


# -- fslint v2: cross-module concurrency rules ------------------------------


def test_concurrency_rules_clean_on_package():
    """The fast-lane concurrency gate: the three whole-package rules
    (`unguarded-shared-state`, `blocking-under-lock`, `lock-order`)
    must report ZERO findings over the merged tree — not baselined,
    zero. Every deliberate design (the engine's tick-owns-the-lock
    scheduler, warmup under `_cv`) carries an inline
    `# fslint: disable=<rule>; <rationale>` at the site, so a hit here
    is either a new concurrency bug or an undocumented design
    decision. The baseline stays empty for these rules by policy."""
    rules = make_rules(select=list(CONCURRENCY_RULE_IDS))
    findings = check_paths([PKG], rules, REPO)
    assert not findings, (
        "concurrency rules fired on the package — fix the race/"
        "inversion or suppress at the site with a rationale:\n"
        + "\n".join(f.render() for f in findings))
    entries = baseline_mod.load_baseline(
        baseline_mod.default_baseline_path(REPO))
    assert not [e for e in entries
                if e["rule"] in CONCURRENCY_RULE_IDS], \
        "concurrency findings must be fixed or line-suppressed, " \
        "never baselined"


def test_dataflow_rules_clean_on_package():
    """The dataflow gate, same policy as the concurrency gate: the
    four PR-17 rules (`donated-buffer-use`, `resource-lifecycle`,
    `api-surface-parity`, `metric-contract`) report ZERO findings
    over the merged tree with an EMPTY baseline. Every real leak the
    sweep found was fixed at the site (serving/engine.py `_admit`,
    serving/handoff.py `adopt_lane`, the bert_dataloader shard
    writers), every donation site uses the rebind idiom, and the
    metrics reference table in docs/observability.md matches the
    registrations — so a hit here is a regression, not legacy debt."""
    rules = make_rules(select=list(DATAFLOW_RULE_IDS))
    findings = check_paths([PKG], rules, REPO)
    assert not findings, (
        "dataflow rules fired on the package — fix the leak/stale "
        "read/contract drift or suppress at the site with a "
        "rationale:\n" + "\n".join(f.render() for f in findings))
    entries = baseline_mod.load_baseline(
        baseline_mod.default_baseline_path(REPO))
    assert not [e for e in entries
                if e["rule"] in DATAFLOW_RULE_IDS], \
        "dataflow findings must be fixed or line-suppressed, " \
        "never baselined"


def test_donation_witness_chain():
    """The bad fixture's finding carries the full witness chain:
    binding line, donating call line, and the stale read."""
    findings = check_file(_fixture("donated-buffer-use", "bad"),
                          make_rules(select=["donated-buffer-use"]),
                          REPO)
    assert len(findings) == 1
    msg = findings[0].message
    assert "donate_argnums bound at" in msg
    assert "donating call at" in msg and "read at" in msg


def test_lifecycle_witness_chains():
    """Both finding kinds fire on the bad fixture, each with its
    witness: the leak names the raising call, the double-release the
    first release site."""
    findings = check_file(_fixture("resource-lifecycle", "bad"),
                          make_rules(select=["resource-lifecycle"]),
                          REPO)
    msgs = sorted(f.message for f in findings)
    assert len(msgs) == 2
    assert any("pad_prompt" in m and "release skipped" in m
               for m in msgs)
    assert any("released twice" in m and "first release" in m
               for m in msgs)


def test_cross_module_lock_discipline(tmp_path):
    """The project index resolves calls ACROSS files: a blocking call
    two modules away from the `with lock:` body is still caught."""
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("", encoding="utf-8")
    (pkg / "transport.py").write_text(textwrap.dedent("""
        import urllib.request

        def fetch(url):
            return urllib.request.urlopen(url).read()
        """), encoding="utf-8")
    (pkg / "router.py").write_text(textwrap.dedent("""
        import threading

        from pkg.transport import fetch


        class Router:
            def __init__(self):
                self._lock = threading.Lock()
                self.state = {}

            def refresh(self, url):
                with self._lock:
                    self.state["health"] = fetch(url)
        """), encoding="utf-8")
    rules = make_rules(select=["blocking-under-lock"])
    findings = check_paths([str(pkg)], rules,
                           project_root=str(tmp_path))
    assert [f.rule for f in findings] == ["blocking-under-lock"]
    assert "pkg/router.py" == findings[0].path
    assert "fetch" in findings[0].message
    assert "urlopen" in findings[0].message


def test_index_cache_invalidates_on_content_change(tmp_path):
    """The on-disk index cache keys per-file entries by content hash:
    editing a file (same path) must re-summarize it, never serve the
    stale summary — the cache can only ever be a speedup."""
    mod = tmp_path / "counter.py"
    clean = textwrap.dedent("""
        import threading


        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0

            def bump(self):
                with self._lock:
                    self._n += 1

            def read(self):
                with self._lock:
                    return self._n
        """)
    mod.write_text(clean, encoding="utf-8")
    cache = str(tmp_path / "cache.json")
    rules = make_rules(select=["unguarded-shared-state"])

    assert not check_paths([str(mod)], rules,
                           project_root=str(tmp_path),
                           index_cache=cache)
    assert os.path.exists(cache)

    # same content, warm cache: still clean (cache round-trips)
    assert not check_paths([str(mod)], rules,
                           project_root=str(tmp_path),
                           index_cache=cache)

    # introduce an unguarded write; the warm cache must not mask it
    mod.write_text(
        clean + "    def reset(self):\n        self._n = 0\n",
        encoding="utf-8")
    findings = check_paths([str(mod)], rules,
                           project_root=str(tmp_path),
                           index_cache=cache)
    assert [f.rule for f in findings] == ["unguarded-shared-state"]
    assert "self._n = 0" == findings[0].code

    # revert: clean again, via the now-twice-rewritten cache
    mod.write_text(clean, encoding="utf-8")
    assert not check_paths([str(mod)], rules,
                           project_root=str(tmp_path),
                           index_cache=cache)


def test_json_deterministic_across_hash_seeds():
    """Byte-identical `--json` output under different
    PYTHONHASHSEED values: the project index iterates sets/dicts in
    sorted order everywhere, so CI can diff reports across hosts.
    Runs over the fixtures tree (known findings, all three concurrency
    rules active) in subprocesses so the seed actually varies."""
    argv = [sys.executable, "-m", "fengshen_tpu.analysis", FIXTURES,
            "--json", "--no-baseline", "--no-index-cache"]
    outs = []
    for seed in ("0", "4242"):
        env = dict(os.environ, PYTHONHASHSEED=seed,
                   JAX_PLATFORMS="cpu")
        proc = subprocess.run(argv, capture_output=True, text=True,
                              timeout=120, env=env, cwd=REPO)
        assert proc.returncode == 1, proc.stderr
        outs.append(proc.stdout)
    assert outs[0] == outs[1], "--json output varies with hash seed"
    report = json.loads(outs[0])
    fired = {f["rule"] for f in report["findings"]}
    assert set(CONCURRENCY_RULE_IDS) <= fired
    assert set(DATAFLOW_RULE_IDS) <= fired


def test_changed_file_discovery(tmp_path):
    """`--changed` file discovery: modified-vs-HEAD plus untracked,
    .py only, deleted files dropped."""
    repo = tmp_path / "repo"
    repo.mkdir()
    env = dict(os.environ, GIT_AUTHOR_NAME="t", GIT_AUTHOR_EMAIL="t@t",
               GIT_COMMITTER_NAME="t", GIT_COMMITTER_EMAIL="t@t")

    def git(*argv):
        subprocess.run(["git", *argv], cwd=str(repo), check=True,
                       capture_output=True, env=env)

    git("init", "-q")
    (repo / "a.py").write_text("A = 1\n", encoding="utf-8")
    (repo / "gone.py").write_text("G = 1\n", encoding="utf-8")
    (repo / "notes.md").write_text("x\n", encoding="utf-8")
    git("add", "-A")
    git("commit", "-qm", "seed")

    (repo / "a.py").write_text("A = 2\n", encoding="utf-8")   # modified
    (repo / "b.py").write_text("B = 1\n", encoding="utf-8")   # untracked
    (repo / "notes.md").write_text("y\n", encoding="utf-8")   # not .py
    (repo / "gone.py").unlink()                               # deleted

    changed = _changed_py_files(str(repo))
    assert [os.path.basename(p) for p in changed] == ["a.py", "b.py"]

    with pytest.raises(RuntimeError):
        _changed_py_files(str(tmp_path))  # not a git repository


def test_cli_github_format(capsys):
    """`--format=github` renders one ::error workflow annotation per
    finding, carrying file/line/col and the rule id."""
    bad = os.path.join(FIXTURES, "lock_order_bad.py")
    rc = fslint_main([bad, "--select", "lock-order", "--no-baseline",
                      "--no-index-cache", "--format=github"])
    assert rc == 1
    out = capsys.readouterr().out.splitlines()
    assert out and all(
        line.startswith("::error file=tests/analysis_fixtures/"
                        "lock_order_bad.py,line=") and
        "title=fslint lock-order::" in line
        for line in out)


def test_sarif_deterministic_across_hash_seeds():
    """`--format=sarif` (the `make lint-ci` artifact) is byte-stable
    across PYTHONHASHSEED values and structurally a SARIF 2.1.0 log:
    one run, rules sorted by id, one result per finding with a
    1-based startColumn."""
    argv = [sys.executable, "-m", "fengshen_tpu.analysis", FIXTURES,
            "--format=sarif", "--no-baseline", "--no-index-cache"]
    outs = []
    for seed in ("0", "4242"):
        env = dict(os.environ, PYTHONHASHSEED=seed,
                   JAX_PLATFORMS="cpu")
        proc = subprocess.run(argv, capture_output=True, text=True,
                              timeout=120, env=env, cwd=REPO)
        assert proc.returncode == 1, proc.stderr
        outs.append(proc.stdout)
    assert outs[0] == outs[1], "SARIF output varies with hash seed"
    log = json.loads(outs[0])
    assert log["version"] == "2.1.0"
    (run,) = log["runs"]
    rule_ids = [r["id"] for r in run["tool"]["driver"]["rules"]]
    assert rule_ids == sorted(rule_ids)
    assert set(RULE_IDS) <= set(rule_ids)
    assert run["results"], "fixtures tree must produce SARIF results"
    for res in run["results"]:
        assert res["level"] == "error" and res["ruleId"] in rule_ids
        region = res["locations"][0]["physicalLocation"]["region"]
        assert region["startLine"] >= 1 and region["startColumn"] >= 1


def test_cli_stats_in_json_report(capsys):
    """`--stats` adds a stats block to the JSON report: files indexed,
    rules run, index-cache hit/miss split, and wall time."""
    bad = os.path.join(FIXTURES, "lock_order_bad.py")
    rc = fslint_main([bad, "--json", "--stats", "--no-baseline",
                      "--no-index-cache"])
    assert rc == 1
    report = json.loads(capsys.readouterr().out)
    stats = report["stats"]
    assert stats["files"] == 1
    assert stats["rules"] == len(make_rules())
    assert stats["index_cache_hits"] == 0      # --no-index-cache
    # no disk cache: the file is either summarised fresh or served
    # from the in-process memo
    assert stats["index_cache_misses"] + stats["memo_hit"] == 1
    assert stats["wall_time_s"] >= 0

    # without --stats the report carries no stats key (determinism:
    # wall time is the one non-reproducible field)
    rc = fslint_main([bad, "--json", "--no-baseline",
                      "--no-index-cache"])
    assert rc == 1
    assert "stats" not in json.loads(capsys.readouterr().out)


@pytest.mark.parametrize("fmt", ["text", "sarif"])
def test_cli_stats_on_stderr_for_non_json(fmt, capsys):
    clean = os.path.join(FIXTURES, "lock_order_clean.py")
    rc = fslint_main([clean, f"--format={fmt}", "--stats",
                      "--no-baseline", "--no-index-cache"])
    assert rc == 0
    err = capsys.readouterr().err
    assert "fslint stats: " in err
    stats = json.loads(err.split("fslint stats: ", 1)[1])
    assert stats["files"] == 1


def test_warm_cache_whole_package_under_budget(tmp_path):
    """Fast-lane smoke: with a warm index cache the whole-package
    index build serves every file summary from the cache — the
    dataflow findings ride in the cached summaries, so nothing is
    re-analyzed — and finishes in a fraction of the cold-build time."""
    import time

    from fengshen_tpu.analysis import engine as engine_mod
    from fengshen_tpu.analysis import project as project_mod

    cache = str(tmp_path / "cache.json")
    files = sorted(engine_mod.iter_py_files([PKG]))

    t0 = time.monotonic()
    cold = project_mod.build_index(files, REPO, cache_path=cache)
    cold_s = time.monotonic() - t0
    stats = dict(project_mod.LAST_BUILD_STATS)
    assert stats["cache_misses"] == stats["files"] > 100

    t0 = time.monotonic()
    warm = project_mod.build_index(files, REPO, cache_path=cache)
    warm_s = time.monotonic() - t0
    stats = dict(project_mod.LAST_BUILD_STATS)
    assert stats["cache_hits"] == stats["files"]
    assert stats["cache_misses"] == 0

    # warm is observed ~20x cheaper than cold (~0.3s vs ~6.5s); a 3x
    # bar with a 2s floor stays green on slow CI while still tripping
    # if the cache stops serving (or the flow engines re-run)
    assert warm_s < max(2.0, cold_s / 3), (cold_s, warm_s)

    # and the round-tripped summaries carry the dataflow facts intact
    rel = "fengshen_tpu/serving/engine.py"
    assert warm.files[rel].lifecycle_findings == \
        cold.files[rel].lifecycle_findings
    assert warm.files[rel].metrics == cold.files[rel].metrics
