"""Beam search / seq2seq decode tests.

The reference's seq2seq examples decode through HF
`model.generate(num_beams=...)` (fengshen/examples/mt5_summary, qa_t5,
finetune_bart_qg); here the equivalent surface is
`utils.generate.seq2seq_generate`. Correctness oracle: brute-force
enumeration of every candidate hypothesis on a tiny model.
"""

import itertools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fengshen_tpu.models.t5 import T5Config, T5ForConditionalGeneration
from fengshen_tpu.utils.generate import seq2seq_generate

pytestmark = pytest.mark.slow  # full-fit/e2e lane: run with -m slow or no -m filter


VOCAB = 6
EOS = 1
PAD = 0
START = 0


@pytest.fixture(scope="module")
def tiny_t5():
    config = T5Config(
        vocab_size=VOCAB, d_model=16, d_kv=4, d_ff=32,
        num_layers=1, num_decoder_layers=1, num_heads=2,
        dtype="float32", param_dtype="float32")
    model = T5ForConditionalGeneration(config)
    params = model.init(
        jax.random.PRNGKey(7), jnp.zeros((1, 4), jnp.int32),
        jnp.zeros((1, 2), jnp.int32))["params"]
    return model, params


def _teacher_forced_logprobs(model, params, src, dec_prefix):
    """log p(token_t | src, dec_prefix[:t]) for every position."""
    dec = jnp.asarray(dec_prefix, jnp.int32)[None]
    logits = model.apply({"params": params}, jnp.asarray(src)[None], dec)
    return np.asarray(
        jax.nn.log_softmax(logits.astype(jnp.float32), -1)[0])


def _batched_logprobs(model, params, src, decs):
    """One apply for a batch of equal-length decoder prefixes."""
    dec = jnp.asarray(decs, jnp.int32)
    srcs = jnp.tile(jnp.asarray(src, jnp.int32)[None], (dec.shape[0], 1))
    logits = model.apply({"params": params}, srcs, dec)
    return np.asarray(jax.nn.log_softmax(logits.astype(jnp.float32), -1))


def _brute_force_best(model, params, src, max_new, length_penalty):
    """Enumerate every hypothesis: eos at step t with a non-eos prefix, or
    no eos within the horizon. Score = sum_logprobs / t**length_penalty,
    matching seq2seq_beam_search's documented semantics."""
    non_eos = [v for v in range(VOCAB) if v != EOS]
    best_score, best_seq = -np.inf, None

    def consider(decs, ts):
        nonlocal best_score, best_seq
        lps = _batched_logprobs(model, params, src,
                                [d[:-1] for d in decs])
        for dec, t, lp in zip(decs, ts, lps):
            total = sum(lp[i, dec[i + 1]] for i in range(t))
            score = total / (t ** length_penalty)
            if score > best_score:
                best_score, best_seq = score, dec

    for t in range(1, max_new + 1):
        decs = [[START] + list(p) + [EOS]
                for p in itertools.product(non_eos, repeat=t - 1)]
        consider(decs, [t] * len(decs))
    decs = [[START] + list(p)
            for p in itertools.product(non_eos, repeat=max_new)]
    consider(decs, [max_new] * len(decs))
    return best_score, best_seq


@pytest.mark.parametrize("length_penalty", [1.0, 0.5])
def test_beam_search_matches_brute_force(tiny_t5, length_penalty):
    model, params = tiny_t5
    src = [2, 3, 4, 5]
    max_new = 3
    # Exactness bound: K ≥ all 25 alive prefixes at depth 2 AND
    # 2K ≥ the 150 candidates of the last expansion → K=75 explores the
    # entire hypothesis space, so beam == brute force.
    out = seq2seq_generate(
        model, params, jnp.asarray(src, jnp.int32)[None],
        max_new_tokens=max_new, decoder_start_token_id=START,
        eos_token_id=EOS, pad_token_id=PAD, num_beams=75,
        length_penalty=length_penalty)
    _, best_seq = _brute_force_best(model, params, src, max_new,
                                    length_penalty)
    got = [int(x) for x in np.asarray(out[0])]
    want = best_seq + [PAD] * (max_new + 1 - len(best_seq))
    assert got == want


def test_beam_one_equals_greedy(tiny_t5):
    model, params = tiny_t5
    src = jnp.asarray([[2, 3, 4, 5], [5, 4, 3, 2]], jnp.int32)
    greedy = seq2seq_generate(
        model, params, src, max_new_tokens=5,
        decoder_start_token_id=START, eos_token_id=EOS, num_beams=1)
    # greedy == step-by-step argmax teacher forcing
    for b in range(2):
        dec = [START]
        for t in range(5):
            lp = _teacher_forced_logprobs(
                model, params, np.asarray(src[b]), dec)
            nxt = int(lp[t].argmax())
            dec.append(nxt)
            if nxt == EOS:
                break
        want = dec + [PAD] * (6 - len(dec))
        assert [int(x) for x in np.asarray(greedy[b])] == want


def test_beam_search_is_at_least_greedy(tiny_t5):
    """Beam K must never score below the greedy hypothesis."""
    model, params = tiny_t5
    src = [2, 5, 3, 2]
    max_new = 4

    def score_of(seq_row):
        toks = [int(x) for x in seq_row]
        dec, t = [toks[0]], 0
        for tok in toks[1:]:
            dec.append(tok)
            t += 1
            if tok == EOS:
                break
            if t == max_new:
                break
        lp = _teacher_forced_logprobs(model, params, src, dec[:-1])
        total = sum(lp[i, dec[i + 1]] for i in range(len(dec) - 1))
        return total / ((len(dec) - 1) ** 1.0)

    greedy = seq2seq_generate(
        model, params, jnp.asarray(src, jnp.int32)[None],
        max_new_tokens=max_new, decoder_start_token_id=START,
        eos_token_id=EOS, num_beams=1)
    beam = seq2seq_generate(
        model, params, jnp.asarray(src, jnp.int32)[None],
        max_new_tokens=max_new, decoder_start_token_id=START,
        eos_token_id=EOS, num_beams=4)
    assert score_of(np.asarray(beam[0])) >= \
        score_of(np.asarray(greedy[0])) - 1e-5


def test_trainer_predict_beam_qa_t5(tmp_path):
    """Trainer.predict drives the qa_t5 module's beam predict_step
    (reference decode surface: finetune_t5_cmrc.py:217-224)."""
    import argparse

    from fengshen_tpu.examples.qa_t5.finetune_t5_cmrc import T5QAModule
    from fengshen_tpu.models.t5 import T5Config
    from fengshen_tpu.trainer import Trainer, add_trainer_args
    from fengshen_tpu.models.model_utils import add_module_args

    parser = argparse.ArgumentParser()
    parser = add_module_args(parser)
    parser = add_trainer_args(parser)
    parser = T5QAModule.add_module_specific_args(parser)
    args = parser.parse_args([
        "--max_target_length", "4", "--num_beams", "2",
        "--default_root_dir", str(tmp_path)])
    module = T5QAModule(args, config=T5Config.small_test_config(
        vocab_size=VOCAB))
    params = module.init_params(jax.random.PRNGKey(0))
    batch = {"input_ids": jnp.asarray([[2, 3, 4, 5]], jnp.int32),
             "attention_mask": jnp.ones((1, 4), jnp.int32)}
    outs = Trainer(args).predict(module, [batch], params=params)
    assert outs[0].shape == (1, 5)
    assert int(outs[0][0, 0]) == module.config.decoder_start_token_id


def _tiny_family(family):
    if family == "t5":
        from fengshen_tpu.models.t5 import (T5Config,
                                            T5ForConditionalGeneration)
        cfg = T5Config(vocab_size=VOCAB, d_model=16, d_kv=4, d_ff=32,
                       num_layers=1, num_decoder_layers=1, num_heads=2,
                       dtype="float32", param_dtype="float32")
        return T5ForConditionalGeneration(cfg)
    if family == "bart":
        from fengshen_tpu.models.bart import (BartConfig,
                                              BartForConditionalGeneration)
        return BartForConditionalGeneration(BartConfig.small_test_config(
            vocab_size=VOCAB, dtype="float32"))
    if family == "pegasus":
        from fengshen_tpu.models.pegasus import (
            PegasusConfig, PegasusForConditionalGeneration)
        return PegasusForConditionalGeneration(
            PegasusConfig.small_test_config(vocab_size=VOCAB,
                                            dtype="float32"))
    from fengshen_tpu.models.deltalm import (
        DeltaLMConfig, DeltaLMForConditionalGeneration)
    return DeltaLMForConditionalGeneration(
        DeltaLMConfig.small_test_config(vocab_size=VOCAB, dtype="float32"))


@pytest.mark.parametrize("family", ["t5", "bart", "pegasus", "deltalm"])
def test_cached_equals_buffer_paths(family, monkeypatch):
    """Every seq2seq family decodes through the KV cache (self + cross);
    forcing the full-prefix buffer fallback must give identical sequences
    for greedy AND beam — the two decode implementations are numerically
    the same decoder (positions, cache masking, cross K/V included)."""
    import importlib
    G = importlib.import_module("fengshen_tpu.utils.generate")
    model = _tiny_family(family)
    src = jnp.asarray([[2, 3, 4, 5], [5, 2, 2, 3]], jnp.int32)
    params = model.init(jax.random.PRNGKey(1), src,
                        src[:, :2])["params"]

    def run():
        greedy = seq2seq_generate(
            model, params, src, max_new_tokens=5,
            decoder_start_token_id=START, eos_token_id=EOS)
        beam = seq2seq_generate(
            model, params, src, max_new_tokens=5,
            decoder_start_token_id=START, eos_token_id=EOS, num_beams=3)
        sampled = seq2seq_generate(
            model, params, src, max_new_tokens=5,
            decoder_start_token_id=START, eos_token_id=EOS,
            do_sample=True, top_k=4, rng=jax.random.PRNGKey(5))
        return np.asarray(greedy), np.asarray(beam), np.asarray(sampled)

    cached = run()
    monkeypatch.setattr(G, "_seq2seq_supports_cache", lambda m: False)
    buffered = run()
    for c, b in zip(cached, buffered):
        np.testing.assert_array_equal(c, b)


def test_full_call_protocol_beam():
    """Models exposing only __call__ (no encode/decode_logits) go through
    the full-forward logits fallback; verify shapes + eos padding."""
    import flax.linen as nn

    class FullCallOnly(nn.Module):
        @nn.compact
        def __call__(self, input_ids, decoder_input_ids,
                     attention_mask=None, deterministic=True):
            emb = nn.Embed(VOCAB, 16)(decoder_input_ids)
            ctx = nn.Embed(VOCAB, 16)(input_ids).mean(1, keepdims=True)
            return nn.Dense(VOCAB)(emb + ctx)

    model = FullCallOnly()
    src = jnp.asarray([[2, 3, 4, 5]], jnp.int32)
    params = model.init(jax.random.PRNGKey(0), src,
                        jnp.zeros((1, 2), jnp.int32))["params"]
    out = seq2seq_generate(
        model, params, src, max_new_tokens=4,
        decoder_start_token_id=START, eos_token_id=EOS, num_beams=3)
    assert out.shape == (1, 5)
    toks = [int(x) for x in np.asarray(out[0])]
    if EOS in toks[1:]:
        after = toks[toks[1:].index(EOS) + 2:]
        assert all(t == PAD for t in after)


def test_pegasus_encode_decode_beam():
    """Pegasus now exposes encode/decode_logits — the generate loop runs
    the encoder once; beam output must match the full-forward greedy
    argmax semantics (decode_logits ≡ __call__ slice)."""
    from fengshen_tpu.models.pegasus import (PegasusConfig,
                                             PegasusForConditionalGeneration)
    config = PegasusConfig(
        vocab_size=VOCAB, d_model=16, encoder_layers=1, decoder_layers=1,
        encoder_attention_heads=2, decoder_attention_heads=2,
        encoder_ffn_dim=32, decoder_ffn_dim=32,
        max_position_embeddings=32, dtype="float32")
    model = PegasusForConditionalGeneration(config)
    src = jnp.asarray([[2, 3, 4, 5]], jnp.int32)
    params = model.init(jax.random.PRNGKey(0), src,
                        jnp.zeros((1, 2), jnp.int32))["params"]
    # decode_logits on a prefix == __call__ on the same prefix
    dec = jnp.asarray([[START, 3, 4]], jnp.int32)
    enc = model.apply({"params": params}, src, method=model.encode)
    via_decode = model.apply({"params": params}, dec, enc,
                             method=model.decode_logits)
    via_call = model.apply({"params": params}, src, dec)
    np.testing.assert_allclose(np.asarray(via_decode),
                               np.asarray(via_call), atol=1e-5)
    out = seq2seq_generate(
        model, params, src, max_new_tokens=4,
        decoder_start_token_id=START, eos_token_id=EOS, num_beams=3)
    assert out.shape == (1, 5)
