"""Image-text dataset/collator tests."""

import csv

import numpy as np


def _make_dataset(tmp_path, n=3, size=40):
    from PIL import Image
    rng = np.random.RandomState(0)
    rows = []
    for i in range(n):
        img = Image.fromarray(rng.randint(0, 255, (size, size + 10, 3),
                                          np.uint8))
        path = tmp_path / f"img_{i}.png"
        img.save(path)
        rows.append({"image": f"img_{i}.png", "caption": f"图片{i}"})
    csv_path = tmp_path / "data.csv"
    with open(csv_path, "w", newline="") as f:
        writer = csv.DictWriter(f, fieldnames=["image", "caption"])
        writer.writeheader()
        writer.writerows(rows)
    return str(csv_path)


class FakeTok:
    def __call__(self, texts, padding=None, truncation=None,
                 max_length=None, return_tensors=None):
        ids = np.zeros((len(texts), max_length), np.int64)
        mask = np.zeros((len(texts), max_length), np.int64)
        for i, t in enumerate(texts):
            n = min(len(t), max_length)
            ids[i, :n] = [3 + (ord(c) % 90) for c in t][:n]
            mask[i, :n] = 1
        return {"input_ids": ids, "attention_mask": mask}


def test_clip_collator(tmp_path):
    from fengshen_tpu.data.clip_dataloader import (ImageTextCSVDataset,
                                                   CLIPCollator)
    ds = ImageTextCSVDataset(_make_dataset(tmp_path))
    assert len(ds) == 3
    coll = CLIPCollator(FakeTok(), image_size=32, max_length=16)
    batch = coll([ds[0], ds[1]])
    assert batch["pixel_values"].shape == (2, 32, 32, 3)
    assert batch["input_ids"].shape == (2, 16)
    # normalised: roughly zero-centred
    assert abs(batch["pixel_values"].mean()) < 3.0


def test_sd_collator(tmp_path):
    from fengshen_tpu.data.clip_dataloader import (ImageTextCSVDataset,
                                                   SDCollator)
    ds = ImageTextCSVDataset(_make_dataset(tmp_path))
    coll = SDCollator(FakeTok(), image_size=16, max_length=8)
    batch = coll([ds[0]])
    assert batch["pixel_values"].shape == (1, 16, 16, 3)
    assert batch["pixel_values"].min() >= -1.0
    assert batch["pixel_values"].max() <= 1.0
