"""Pegasus golden-value parity vs HF torch."""

import jax.numpy as jnp
import numpy as np
import pytest


def test_pegasus_forward_parity():
    torch = pytest.importorskip("torch")
    import transformers
    from fengshen_tpu.models.pegasus import (PegasusConfig,
                                             PegasusForConditionalGeneration)
    hf_cfg = transformers.PegasusConfig(
        vocab_size=128, d_model=32, encoder_layers=2, decoder_layers=2,
        encoder_attention_heads=4, decoder_attention_heads=4,
        encoder_ffn_dim=64, decoder_ffn_dim=64,
        max_position_embeddings=64, scale_embedding=True,
        attn_implementation="eager")
    torch.manual_seed(0)
    tm = transformers.PegasusForConditionalGeneration(hf_cfg).eval()
    cfg = PegasusConfig(vocab_size=128, d_model=32, encoder_layers=2,
                        decoder_layers=2, encoder_attention_heads=4,
                        decoder_attention_heads=4, encoder_ffn_dim=64,
                        decoder_ffn_dim=64, max_position_embeddings=64,
                        scale_embedding=True, dtype="float32")
    sd = tm.state_dict()

    def t(n):
        return sd[n].detach().numpy()

    def lin(p):
        return {"kernel": t(f"{p}.weight").T, "bias": t(f"{p}.bias")}

    def ln(p):
        return {"scale": t(f"{p}.weight"), "bias": t(f"{p}.bias")}

    def attn(p):
        return {x: lin(f"{p}.{x}")
                for x in ("q_proj", "k_proj", "v_proj", "out_proj")}

    params = {"shared": {"embedding": t("model.shared.weight")},
              "encoder_layer_norm": ln("model.encoder.layer_norm"),
              "decoder_layer_norm": ln("model.decoder.layer_norm"),
              "final_logits_bias": t("final_logits_bias").reshape(-1)}
    for i in range(2):
        pre = f"model.encoder.layers.{i}"
        params[f"encoder_layer_{i}"] = {
            "self_attn": attn(f"{pre}.self_attn"),
            "self_attn_layer_norm": ln(f"{pre}.self_attn_layer_norm"),
            "fc1": lin(f"{pre}.fc1"), "fc2": lin(f"{pre}.fc2"),
            "final_layer_norm": ln(f"{pre}.final_layer_norm")}
        pre = f"model.decoder.layers.{i}"
        params[f"decoder_layer_{i}"] = {
            "self_attn": attn(f"{pre}.self_attn"),
            "self_attn_layer_norm": ln(f"{pre}.self_attn_layer_norm"),
            "encoder_attn": attn(f"{pre}.encoder_attn"),
            "encoder_attn_layer_norm": ln(f"{pre}.encoder_attn_layer_norm"),
            "fc1": lin(f"{pre}.fc1"), "fc2": lin(f"{pre}.fc2"),
            "final_layer_norm": ln(f"{pre}.final_layer_norm")}

    enc_ids = np.array([[5, 17, 9, 42, 1]], dtype=np.int32)
    dec_ids = np.array([[0, 5, 17, 9]], dtype=np.int32)
    logits = PegasusForConditionalGeneration(cfg).apply(
        {"params": params}, jnp.asarray(enc_ids), jnp.asarray(dec_ids))
    with torch.no_grad():
        ref = tm(input_ids=torch.tensor(enc_ids, dtype=torch.long),
                 decoder_input_ids=torch.tensor(dec_ids, dtype=torch.long)
                 ).logits.numpy()
    np.testing.assert_allclose(np.asarray(logits), ref, atol=2e-3)
