"""Repo lint: no new blanket exception handlers in fengshen_tpu/.

Resilience code lives or dies on exception discipline — a bare
`except:` / `except Exception:` that swallows a real error turns a
crash into a silently-wrong run (the exact failure mode the rewind and
retry machinery exists to make LOUD). Blanket handlers must either
carry an explicit justification marker on the same line
(`# noqa: BLE001` for re-raise/bounded-retry sites, `# pragma: no
cover` for defensive probes) or sit in the legacy allowlist below.
Do not grow the allowlist — annotate new sites instead.
"""

import os
import re

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "fengshen_tpu")

#: pre-existing unannotated sites (file-relative to fengshen_tpu/);
#: shrink, never grow
LEGACY_ALLOWLIST = {
    "parallel/partition.py",
    "data/megatron_dataloader/helpers.py",
}

MARKERS = ("# noqa: BLE001", "# pragma: no cover")
BLANKET = re.compile(r"^\s*except(\s*:|\s+(Exception|BaseException)\b)")


def _py_files():
    for dirpath, _, filenames in os.walk(PKG):
        for fn in filenames:
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def test_no_unannotated_blanket_excepts():
    violations = []
    for path in _py_files():
        rel = os.path.relpath(path, PKG)
        with open(path, encoding="utf-8") as f:
            for lineno, line in enumerate(f, start=1):
                if not BLANKET.match(line):
                    continue
                if any(marker in line for marker in MARKERS):
                    continue
                if rel in LEGACY_ALLOWLIST:
                    continue
                violations.append(f"{rel}:{lineno}: {line.strip()}")
    assert not violations, (
        "blanket exception handler(s) without a justification marker "
        "(`# noqa: BLE001` or `# pragma: no cover` on the same line):\n"
        + "\n".join(violations))


def test_legacy_allowlist_is_not_stale():
    """Every allowlisted file must still contain an unannotated blanket
    handler — otherwise the entry should be deleted."""
    for rel in sorted(LEGACY_ALLOWLIST):
        path = os.path.join(PKG, rel)
        if not os.path.exists(path):
            pytest.fail(f"allowlist entry {rel} no longer exists")
        with open(path, encoding="utf-8") as f:
            hits = [line for line in f
                    if BLANKET.match(line)
                    and not any(m in line for m in MARKERS)]
        assert hits, f"allowlist entry {rel} is stale — remove it"
