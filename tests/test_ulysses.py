"""Ulysses (all-to-all) sequence parallelism tests on the CPU mesh.

Parity contract: `ulysses_attention_sharded` must match unsharded dense
attention exactly like `ring_attention_sharded` does (tests/test_parallel.py)
— same inputs, same masks — and the `sequence_parallel_attention`
dispatcher must pick the right scheme from head divisibility.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fengshen_tpu.ops import dot_product_attention, causal_mask
from fengshen_tpu.ops.ulysses_attention import (
    ulysses_attention_sharded, sequence_parallel_attention)

pytestmark = pytest.mark.slow  # full-fit/e2e lane: run with -m slow or no -m filter


def _rand_qkv(rng, batch, seq, heads, dim):
    return (jnp.asarray(rng.randn(batch, seq, heads, dim), jnp.float32),
            jnp.asarray(rng.randn(batch, seq, heads, dim), jnp.float32),
            jnp.asarray(rng.randn(batch, seq, heads, dim), jnp.float32))


def test_ulysses_matches_dense_causal(mesh_seq4):
    q, k, v = _rand_qkv(np.random.RandomState(0), 2, 16, 4, 8)
    ref = dot_product_attention(q, k, v, mask=causal_mask(16)[None, None])
    out = ulysses_attention_sharded(q, k, v, mesh=mesh_seq4, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4)


def test_ulysses_non_causal(mesh_seq4):
    q, k, v = _rand_qkv(np.random.RandomState(1), 1, 8, 4, 4)
    ref = dot_product_attention(q, k, v)
    out = ulysses_attention_sharded(q, k, v, mesh=mesh_seq4, causal=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4)


def test_ulysses_segment_ids(mesh_seq4):
    """Padded batch via segment ids: valid rows match dense-with-mask."""
    rng = np.random.RandomState(2)
    batch, seq = 2, 16
    q, k, v = _rand_qkv(rng, batch, seq, 4, 8)
    n_valid = 11
    seg = jnp.asarray(
        np.repeat([[1] * n_valid + [0] * (seq - n_valid)], batch, 0),
        jnp.int32)
    out = ulysses_attention_sharded(q, k, v, segment_ids=seg,
                                    mesh=mesh_seq4, causal=True)
    mask = (seg[:, None, None, :] > 0) & causal_mask(seq)[None, None]
    ref = dot_product_attention(q, k, v, mask=mask)
    np.testing.assert_allclose(np.asarray(out)[:, :n_valid],
                               np.asarray(ref)[:, :n_valid], atol=1e-4)


def test_ulysses_gradients_match_dense(mesh_seq4):
    """a2a collectives must be transparent to autodiff."""
    q, k, v = _rand_qkv(np.random.RandomState(3), 1, 16, 4, 8)

    def loss_sharded(q, k, v):
        return ulysses_attention_sharded(q, k, v, mesh=mesh_seq4,
                                         causal=True).sum()

    def loss_ref(q, k, v):
        return dot_product_attention(
            q, k, v, mask=causal_mask(16)[None, None]).sum()

    gs = jax.grad(loss_sharded, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gs, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_ulysses_rejects_indivisible_heads(mesh_seq4):
    # 3 heads on a sequence=4 mesh cannot a2a-shard
    q, k, v = _rand_qkv(np.random.RandomState(4), 1, 16, 3, 8)
    with pytest.raises(ValueError, match="divisible"):
        ulysses_attention_sharded(q, k, v, mesh=mesh_seq4, causal=True)


def test_dispatcher_auto_picks_by_heads(mesh_seq4):
    # 4 heads / sp=4 -> ulysses; 3 heads -> ring; both must match dense
    for heads in (4, 3):
        q, k, v = _rand_qkv(np.random.RandomState(heads), 1, 16, heads, 8)
        ref = dot_product_attention(q, k, v,
                                    mask=causal_mask(16)[None, None])
        out = sequence_parallel_attention(q, k, v, mesh=mesh_seq4,
                                          causal=True, prefer="auto")
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-4)


def test_dispatcher_no_sequence_axis_falls_back(mesh8):
    # sequence degree 1: plain flash path, still correct
    q, k, v = _rand_qkv(np.random.RandomState(7), 1, 16, 4, 8)
    ref = dot_product_attention(q, k, v, mask=causal_mask(16)[None, None])
    out = sequence_parallel_attention(q, k, v, mesh=mesh8, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4)


def test_llama_ulysses_matches_dense(mesh_seq4):
    """Model-level: a padded batch through attention_impl='ulysses' on a
    sequence=4 mesh matches the dense path on valid rows (the same
    contract as test_llama.py's flash-vs-dense check)."""
    import dataclasses
    from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig(vocab_size=64, hidden_size=32, intermediate_size=64,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=4, max_position_embeddings=16,
                      rms_norm_eps=1e-6, dtype="float32")
    model_d = LlamaForCausalLM(dataclasses.replace(
        cfg, attention_impl="dense"))
    model_u = LlamaForCausalLM(dataclasses.replace(
        cfg, attention_impl="ulysses"))
    ids = np.asarray(
        np.random.RandomState(0).randint(0, 64, (2, 16)), np.int32)
    mask = np.ones((2, 16), np.int32)
    mask[1, 10:] = 0
    params = model_d.init(jax.random.PRNGKey(0), jnp.asarray(ids))["params"]
    out_d = model_d.apply({"params": params}, jnp.asarray(ids),
                          attention_mask=jnp.asarray(mask))
    out_u = model_u.apply({"params": params}, jnp.asarray(ids),
                          attention_mask=jnp.asarray(mask))
    valid = np.asarray(mask, bool)
    np.testing.assert_allclose(np.asarray(out_u)[valid],
                               np.asarray(out_d)[valid], atol=2e-3)
