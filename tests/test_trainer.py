"""End-to-end trainer + sampler + datamodule tests on the 8-device mesh."""

import argparse
import json
import os



import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fengshen_tpu.data import (PretrainingSampler, PretrainingRandomSampler,
                               UniversalDataModule, DataLoader)

pytestmark = pytest.mark.slow  # full-fit/e2e lane: run with -m slow or no -m filter


def _parse(argv, extra=None):
    from fengshen_tpu.trainer import add_trainer_args
    from fengshen_tpu.models.model_utils import add_module_args
    from fengshen_tpu.data.universal_datamodule import UniversalDataModule
    from fengshen_tpu.utils import UniversalCheckpoint
    parser = argparse.ArgumentParser()
    add_module_args(parser)
    add_trainer_args(parser)
    UniversalDataModule.add_data_specific_args(parser)
    UniversalCheckpoint.add_argparse_args(parser)
    return parser.parse_args(argv)


# -- samplers (math parity with reference universal_sampler.py) ----------

def test_pretraining_sampler_resume():
    s = PretrainingSampler(total_samples=20, consumed_samples=8,
                           micro_batch_size=2, data_parallel_rank=0,
                           data_parallel_size=2)
    batches = list(s)
    # starts at 8: global batch [8,9,10,11] → rank0 gets [8,9]
    assert batches[0] == [8, 9]
    s1 = PretrainingSampler(total_samples=20, consumed_samples=8,
                            micro_batch_size=2, data_parallel_rank=1,
                            data_parallel_size=2)
    assert list(s1)[0] == [10, 11]


def test_pretraining_sampler_validates():
    with pytest.raises(ValueError):
        PretrainingSampler(0, 0, 1, 0, 1)
    with pytest.raises(ValueError):
        PretrainingSampler(10, 10, 1, 0, 1)
    with pytest.raises(ValueError):
        PretrainingSampler(10, 0, 1, 3, 2)


def test_random_sampler_resume_mid_epoch():
    """Resuming from consumed_samples must continue the same permutation —
    the property the reference relies on for mid-epoch restart
    (reference: universal_sampler.py:99-122)."""
    full = PretrainingRandomSampler(total_samples=32, consumed_samples=0,
                                    micro_batch_size=2, data_parallel_rank=0,
                                    data_parallel_size=2, epoch_seed=7)
    all_batches = []
    for i, b in enumerate(full):
        all_batches.append(b)
        if i == 7:
            break

    resumed = PretrainingRandomSampler(total_samples=32, consumed_samples=16,
                                       micro_batch_size=2,
                                       data_parallel_rank=0,
                                       data_parallel_size=2, epoch_seed=7)
    resumed_batches = [b for _, b in zip(range(4), resumed)]
    assert resumed_batches == all_batches[4:8]


def test_random_sampler_disjoint_ranks():
    r0 = PretrainingRandomSampler(32, 0, 2, 0, 2, epoch_seed=1)
    r1 = PretrainingRandomSampler(32, 0, 2, 1, 2, epoch_seed=1)
    i0 = {i for b in r0 for i in b}
    i1 = {i for b in r1 for i in b}
    assert i0.isdisjoint(i1)
    assert len(i0 | i1) == 32


def test_random_sampler_epoch_reshuffle():
    e0 = list(PretrainingRandomSampler(16, 0, 2, 0, 1, epoch_seed=3))
    e1 = list(PretrainingRandomSampler(16, 16, 2, 0, 1, epoch_seed=3))
    assert e0 != e1  # new epoch, new permutation
    assert sorted(i for b in e0 for i in b) == \
        sorted(i for b in e1 for i in b)


# -- datamodule ----------------------------------------------------------

def test_datamodule_from_json(tmp_path):
    train = tmp_path / "train.json"
    with open(train, "w") as f:
        for i in range(32):
            f.write(json.dumps({"input_ids": list(range(i, i + 8))}) + "\n")
    args = _parse(["--train_file", str(train), "--train_batchsize", "4",
                   "--sampler_type", "single"])
    dm = UniversalDataModule(args=args)
    loader = dm.train_dataloader()
    batch = next(iter(loader))
    assert batch["input_ids"].shape == (4, 8)
    assert loader.global_batch_size == 4


# -- end-to-end fit ------------------------------------------------------

def test_fit_tiny_llama_8dev(mesh8, tmp_path):
    """Full fit(): sharded init, jit train step with accumulation, metrics
    log — the minimum end-to-end slice of SURVEY.md §7 step 3."""
    from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from fengshen_tpu.trainer import Trainer
    from fengshen_tpu.trainer.modules import CausalLMModule

    cfg = LlamaConfig.small_test_config(dtype="float32")
    model = LlamaForCausalLM(cfg)

    rng = np.random.RandomState(0)
    data = [{"input_ids": rng.randint(0, 255, 16).tolist()}
            for _ in range(64)]

    class ListDS:
        def __len__(self):
            return len(data)

        def __getitem__(self, i):
            return data[i]

    args = _parse(["--max_steps", "4", "--train_batchsize", "8",
                   "--accumulate_grad_batches", "2",
                   "--learning_rate", "1e-3", "--warmup_steps", "1",
                   "--log_every_n_steps", "1",
                   "--default_root_dir", str(tmp_path)])
    module = CausalLMModule(args, model, cfg)
    dm = UniversalDataModule(args=args, datasets={"train": ListDS()})
    trainer = Trainer(args)
    state = trainer.fit(module, dm)
    assert int(state.step) == 4
    lines = [json.loads(l) for l in
             open(os.path.join(tmp_path, "metrics.jsonl"))]
    losses = [l["loss"] for l in lines if "loss" in l]
    assert len(losses) == 4
    assert all(np.isfinite(losses))
    # params actually sharded per the rules
    flat = jax.tree_util.tree_leaves_with_path(state.params)
    from jax.sharding import PartitionSpec as P
    specs = {jax.tree_util.keystr(k): v.sharding.spec for k, v in flat}
    assert any(s != P() and s != P(None, None) for s in specs.values())


def test_dataloader_peek_does_not_advance():
    data = [{"input_ids": [i] * 4} for i in range(16)]

    class DS:
        def __len__(self):
            return 16

        def __getitem__(self, i):
            return data[i]

    s = PretrainingRandomSampler(16, 0, 2, 0, 1, epoch_seed=5)
    loader = DataLoader(DS(), s, global_batch_size=2)
    peeked = loader.peek()
    assert peeked["input_ids"].shape == (2, 4)
    assert s.consumed_samples == 0
    first = next(iter(loader))
    # a fresh sampler must yield the same first batch
    s2 = PretrainingRandomSampler(16, 0, 2, 0, 1, epoch_seed=5)
    first2 = next(iter(DataLoader(DS(), s2, global_batch_size=2)))
    np.testing.assert_array_equal(first["input_ids"], first2["input_ids"])


def test_total_steps_epochs_not_squared():
    from fengshen_tpu.models.model_utils import get_total_steps
    args = argparse.Namespace(max_steps=-1, max_epochs=3)
    assert get_total_steps(args, dataset_len=100, world_batch=10) == 30


def test_scan_export_roundtrip():
    from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from fengshen_tpu.models.llama.convert import (params_to_torch_state,
                                                   torch_to_params)
    cfg = LlamaConfig.small_test_config(dtype="float32", scan_layers=True)
    model = LlamaForCausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    state = params_to_torch_state(params, cfg)
    back = torch_to_params(state, cfg)
    k0 = params["model"]["layers"]["layer"]["self_attn"]["q_proj"]["kernel"]
    k1 = back["model"]["layers"]["layer"]["self_attn"]["q_proj"]["kernel"]
    np.testing.assert_allclose(np.asarray(k0), np.asarray(k1), atol=1e-6)


def test_preemption_autosave(mesh8, tmp_path):
    """SIGTERM-style preemption flag triggers a checkpoint and clean exit."""
    from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from fengshen_tpu.trainer import Trainer
    from fengshen_tpu.trainer.modules import CausalLMModule
    from fengshen_tpu.utils import UniversalCheckpoint

    cfg = LlamaConfig.small_test_config(dtype="float32")
    model = LlamaForCausalLM(cfg)
    rng = np.random.RandomState(0)
    data = [{"input_ids": rng.randint(0, 255, 16).tolist()}
            for _ in range(64)]

    class DS:
        def __len__(self):
            return 64

        def __getitem__(self, i):
            return data[i]

    args = _parse(["--max_steps", "50", "--train_batchsize", "8",
                   "--log_every_n_steps", "1", "--warmup_steps", "1",
                   "--default_root_dir", str(tmp_path),
                   "--save_ckpt_path", str(tmp_path / "ck"),
                   "--load_ckpt_path", str(tmp_path / "none")])
    from fengshen_tpu.data import UniversalDataModule
    module = CausalLMModule(args, model, cfg)
    dm = UniversalDataModule(args=args, datasets={"train": DS()})
    trainer = Trainer(args)
    cb = UniversalCheckpoint(args)
    trainer.callbacks.append(cb)

    # preempt after step 2 via the step-end hook
    class Preemptor:
        def on_train_step_end(self, tr, state):
            if tr.global_step == 2:
                tr._preempted = True

    trainer.callbacks.append(Preemptor())
    state = trainer.fit(module, dm)
    assert int(state.step) == 2  # stopped early
    import orbax.checkpoint as ocp
    mgr = ocp.CheckpointManager(str(tmp_path / "ck"))
    assert mgr.latest_step() == 2  # autosaved at preemption


def test_offload_optimizer_state_lives_on_host(tmp_path, mesh8):
    """ZeRO-offload analog (VERDICT r1 item 7): with --offload_optimizer,
    adam moments live in host memory, device bytes shrink accordingly, and
    training still runs end-to-end."""
    import argparse
    import jax
    import jax.numpy as jnp
    import numpy as np

    from fengshen_tpu.data import UniversalDataModule
    from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from fengshen_tpu.models.model_utils import add_module_args
    from fengshen_tpu.trainer import Trainer, add_trainer_args
    from fengshen_tpu.trainer.modules import CausalLMModule

    parser = argparse.ArgumentParser()
    add_module_args(parser)
    add_trainer_args(parser)
    UniversalDataModule.add_data_specific_args(parser)
    args = parser.parse_args([
        "--max_steps", "2", "--train_batchsize", "4",
        "--log_every_n_steps", "1", "--warmup_steps", "1",
        "--default_root_dir", str(tmp_path), "--offload_optimizer",
        "--fsdp_parallel_size", "2", "--tensor_model_parallel_size", "2",
        "--data_parallel_size", "2"])

    config = LlamaConfig(vocab_size=128, hidden_size=32,
                         intermediate_size=64, num_hidden_layers=2,
                         num_attention_heads=4,
                         max_position_embeddings=32, dtype="float32")
    rng = np.random.RandomState(0)
    rows = [{"input_ids": rng.randint(0, 127, 16).tolist()}
            for _ in range(16)]

    class ListDS:
        def __len__(self):
            return len(rows)

        def __getitem__(self, i):
            return rows[i]

    module = CausalLMModule(args, LlamaForCausalLM(config), config)
    dm = UniversalDataModule(args=args, datasets={"train": ListDS()})
    trainer = Trainer(args)
    state = trainer.fit(module, dm)
    assert int(state.step) == 2

    from fengshen_tpu.trainer.memory import probe_memory_capabilities
    caps = probe_memory_capabilities()
    host_kind = caps.host_kind  # probe-resolved (docs/offload.md):
    # pinned_host where the backend has it, unpinned_host on this build

    def mem_kinds(tree):
        return {leaf.sharding.memory_kind
                for leaf in jax.tree_util.tree_leaves(tree)
                if hasattr(leaf, "sharding")}

    assert mem_kinds(state.opt_state) == {host_kind}
    assert mem_kinds(state.params) == {caps.device_memory_kind}

    # the device footprint must equal params ALONE: every optimizer-state
    # byte lives on the host (vs params+opt on device without offload).
    # Byte accounting by kind is only meaningful when the host space is
    # DISTINCT from the device default (on the CPU backend they are the
    # same space, so placement there is a no-op by construction)
    def nbytes(tree, kind=None):
        return sum(leaf.nbytes for leaf in jax.tree_util.tree_leaves(tree)
                   if hasattr(leaf, "sharding") and
                   (kind is None or leaf.sharding.memory_kind == kind))

    params_total = nbytes(state.params)
    opt_total = nbytes(state.opt_state)
    assert opt_total > 0
    assert nbytes(state.opt_state, host_kind) == opt_total
    if host_kind != caps.device_memory_kind:
        device_bytes = nbytes(state.params, caps.device_memory_kind) + \
            nbytes(state.opt_state, caps.device_memory_kind)
        assert nbytes(state.opt_state, caps.device_memory_kind) == 0
        assert device_bytes == params_total
        assert device_bytes < params_total + opt_total


def test_offload_levels_bit_identical_to_monolithic_step(tmp_path, mesh8):
    """Parity across the offload ladder (docs/offload.md): the
    offloaded two-program step at every resolvable level — and the
    deprecated --offload_optimizer spelling, and --offload=auto —
    produces BIT-identical params to the monolithic fused optax step.
    Placement moves bytes, never math."""
    import argparse

    import jax
    import numpy as np

    from fengshen_tpu.data import UniversalDataModule
    from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from fengshen_tpu.models.model_utils import add_module_args
    from fengshen_tpu.trainer import Trainer, add_trainer_args
    from fengshen_tpu.trainer.modules import CausalLMModule

    rng = np.random.RandomState(0)
    rows = [{"input_ids": rng.randint(0, 127, 16).tolist()}
            for _ in range(16)]

    class ListDS:
        def __len__(self):
            return len(rows)

        def __getitem__(self, i):
            return rows[i]

    config = LlamaConfig(vocab_size=128, hidden_size=32,
                         intermediate_size=64, num_hidden_layers=2,
                         num_attention_heads=4,
                         max_position_embeddings=32, dtype="float32")

    def fit(tag, extra):
        parser = argparse.ArgumentParser()
        add_module_args(parser)
        add_trainer_args(parser)
        UniversalDataModule.add_data_specific_args(parser)
        args = parser.parse_args([
            "--max_steps", "3", "--train_batchsize", "4",
            "--log_every_n_steps", "1", "--warmup_steps", "1",
            "--default_root_dir", str(tmp_path / tag),
            "--fsdp_parallel_size", "2",
            "--tensor_model_parallel_size", "2",
            "--data_parallel_size", "2", *extra])
        module = CausalLMModule(args, LlamaForCausalLM(config), config)
        dm = UniversalDataModule(args=args, datasets={"train": ListDS()})
        trainer = Trainer(args)
        state = trainer.fit(module, dm)
        return state, trainer._offload_policy

    ref, ref_policy = fit("none", ["--offload", "none"])
    assert ref_policy.level == "none"
    ref_leaves = jax.tree_util.tree_leaves(ref.params)
    variants = {
        "auto": ["--offload", "auto"],
        "opt": ["--offload", "opt"],
        "opt_master": ["--offload", "opt_master"],
        "legacy": ["--offload_optimizer"],
    }
    expected_level = {"auto": "none", "opt": "opt",
                      "opt_master": "opt_master", "legacy": "opt"}
    for tag, extra in variants.items():
        state, policy = fit(tag, extra)
        assert policy.level == expected_level[tag], tag
        for a, b in zip(ref_leaves,
                        jax.tree_util.tree_leaves(state.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                          err_msg=f"--offload {tag}")


def test_profiler_trace_hook(tmp_path, mesh8):
    """--profile_steps captures a jax.profiler trace during fit
    (VERDICT r1 item 10)."""
    import argparse
    import os
    import numpy as np

    from fengshen_tpu.data import UniversalDataModule
    from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from fengshen_tpu.models.model_utils import add_module_args
    from fengshen_tpu.trainer import Trainer, add_trainer_args
    from fengshen_tpu.trainer.modules import CausalLMModule

    parser = argparse.ArgumentParser()
    add_module_args(parser)
    add_trainer_args(parser)
    UniversalDataModule.add_data_specific_args(parser)
    args = parser.parse_args([
        "--max_steps", "3", "--train_batchsize", "2",
        "--log_every_n_steps", "1", "--warmup_steps", "1",
        "--default_root_dir", str(tmp_path), "--profile_steps", "1,2"])

    config = LlamaConfig(vocab_size=64, hidden_size=32,
                         intermediate_size=64, num_hidden_layers=1,
                         num_attention_heads=4,
                         max_position_embeddings=16, dtype="float32")
    rng = np.random.RandomState(0)
    rows = [{"input_ids": rng.randint(0, 63, 8).tolist()}
            for _ in range(8)]

    class ListDS:
        def __len__(self):
            return len(rows)

        def __getitem__(self, i):
            return rows[i]

    module = CausalLMModule(args, LlamaForCausalLM(config), config)
    dm = UniversalDataModule(args=args, datasets={"train": ListDS()})
    state = Trainer(args).fit(module, dm)
    assert int(state.step) == 3
    prof_dir = tmp_path / "profile"
    assert prof_dir.is_dir()
    traced = [f for _, _, fs in os.walk(prof_dir) for f in fs]
    assert traced, "no trace files written"


def test_two_process_distributed_initialize():
    """The multi-host bootstrap rendezvous works: two CPU processes join
    one jax.distributed cluster and see the combined device count
    (docs/multihost.md dry-run recipe; VERDICT r1 item 9)."""
    import subprocess
    import sys

    code = """
import sys
import jax
jax.config.update("jax_platforms", "cpu")
from fengshen_tpu.parallel import distributed_initialize

distributed_initialize("127.0.0.1:29876", num_processes=2,
                       process_id=int(sys.argv[1]))
print("DEVICES", jax.device_count(), "PROC", jax.process_count())
"""
    procs = [subprocess.Popen(
        [sys.executable, "-c", code, str(i)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd="/root/repo")
        for i in range(2)]
    outs = [p.communicate(timeout=120)[0] for p in procs]
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out
        assert "PROC 2" in out, out


def test_offload_optimizer_checkpoint_roundtrip(tmp_path, mesh8):
    """Offloaded (host-resident) optimizer state must survive an orbax
    save + restore and come back onto the host memory space."""
    from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from fengshen_tpu.trainer import Trainer
    from fengshen_tpu.trainer.modules import CausalLMModule
    from fengshen_tpu.utils import UniversalCheckpoint

    def build_args(extra=()):
        return _parse([
            "--train_batchsize", "4", "--log_every_n_steps", "1",
            "--warmup_steps", "1", "--default_root_dir", str(tmp_path),
            "--save_ckpt_path", str(tmp_path / "ckpt"),
            "--load_ckpt_path", str(tmp_path / "ckpt"),
            "--offload_optimizer", *extra])

    config = LlamaConfig(vocab_size=64, hidden_size=32,
                         intermediate_size=64, num_hidden_layers=1,
                         num_attention_heads=4,
                         max_position_embeddings=16, dtype="float32")
    rng = np.random.RandomState(0)
    rows = [{"input_ids": rng.randint(0, 63, 8).tolist()}
            for _ in range(16)]

    class ListDS:
        def __len__(self):
            return len(rows)

        def __getitem__(self, i):
            return rows[i]

    # run 2 steps and save
    args = build_args(["--max_steps", "2", "--every_n_train_steps", "2"])
    trainer = Trainer(args)
    trainer.callbacks.append(UniversalCheckpoint(args))
    module = CausalLMModule(args, LlamaForCausalLM(config), config)
    dm = UniversalDataModule(args=args, datasets={"train": ListDS()})
    state = trainer.fit(module, dm)
    assert int(state.step) == 2

    # fresh trainer restores and continues, moments back on the host
    args2 = build_args(["--max_steps", "4"])
    trainer2 = Trainer(args2)
    trainer2.callbacks.append(UniversalCheckpoint(args2))
    module2 = CausalLMModule(args2, LlamaForCausalLM(config), config)
    dm2 = UniversalDataModule(args=args2, datasets={"train": ListDS()})
    state2 = trainer2.fit(module2, dm2)
    assert trainer2.global_step == 4 and int(state2.step) == 4
    from fengshen_tpu.trainer.memory import probe_memory_capabilities
    kinds = {leaf.sharding.memory_kind
             for leaf in jax.tree_util.tree_leaves(state2.opt_state)
             if hasattr(leaf, "sharding")}
    # host kind is probe-resolved (docs/offload.md): pinned_host where
    # the backend has it, unpinned_host on this CPU build
    assert kinds == {probe_memory_capabilities().host_kind}


def test_async_checkpoint_save_and_resume(tmp_path, mesh8):
    """--async_save: periodic saves return without blocking, the final
    flush lands a complete restorable checkpoint."""
    import argparse
    import time

    import jax
    import numpy as np

    from fengshen_tpu.data import UniversalDataModule
    from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from fengshen_tpu.models.model_utils import add_module_args
    from fengshen_tpu.trainer import Trainer, add_trainer_args
    from fengshen_tpu.trainer.modules import CausalLMModule
    from fengshen_tpu.utils import UniversalCheckpoint

    parser = argparse.ArgumentParser()
    add_module_args(parser)
    add_trainer_args(parser)
    UniversalDataModule.add_data_specific_args(parser)
    UniversalCheckpoint.add_argparse_args(parser)
    ckpt_dir = tmp_path / "ckpt"
    args = parser.parse_args([
        "--max_steps", "4", "--train_batchsize", "4",
        "--every_n_train_steps", "2", "--async_save",
        "--log_every_n_steps", "1", "--warmup_steps", "1",
        "--save_ckpt_path", str(ckpt_dir),
        "--load_ckpt_path", str(ckpt_dir),
        "--default_root_dir", str(tmp_path)])
    config = LlamaConfig(vocab_size=64, hidden_size=16,
                         intermediate_size=32, num_hidden_layers=1,
                         num_attention_heads=2,
                         max_position_embeddings=32, dtype="float32")
    rows = [{"input_ids":
             np.random.RandomState(i).randint(0, 63, 16).tolist()}
            for i in range(32)]

    class DS:
        def __len__(self):
            return len(rows)

        def __getitem__(self, i):
            return rows[i]

    trainer = Trainer(args)
    module = CausalLMModule(args, LlamaForCausalLM(config), config)
    cb = UniversalCheckpoint(args)
    trainer.callbacks.append(cb)
    state = trainer.fit(module, UniversalDataModule(
        args=args, datasets={"train": DS()}))
    cb.wait()
    # both periodic steps landed and are restorable
    import orbax.checkpoint as ocp
    mgr = ocp.CheckpointManager(str(ckpt_dir.resolve()))
    assert mgr.latest_step() == 4
    trainer2 = Trainer(args)
    trainer2.callbacks.append(UniversalCheckpoint(args))
    state2 = trainer2.restore_for_predict(module)
    leaves1 = jax.tree_util.tree_leaves(state.params)
    leaves2 = jax.tree_util.tree_leaves(state2.params)
    np.testing.assert_allclose(np.asarray(leaves1[0]),
                               np.asarray(leaves2[0]), rtol=1e-6)


def _fit_tiny(tmp_path, extra_args, seed_data=7):
    """Shared driver for the steps_per_execution parity test."""
    from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from fengshen_tpu.trainer import Trainer
    from fengshen_tpu.trainer.modules import CausalLMModule

    cfg = LlamaConfig.small_test_config(dtype="float32")
    model = LlamaForCausalLM(cfg)
    rng = np.random.RandomState(seed_data)
    data = [{"input_ids": rng.randint(0, 255, 16).tolist()}
            for _ in range(64)]

    class ListDS:
        def __len__(self):
            return len(data)

        def __getitem__(self, i):
            return data[i]

    args = _parse(["--max_steps", "4", "--train_batchsize", "8",
                   "--learning_rate", "1e-3", "--warmup_steps", "1",
                   "--log_every_n_steps", "1",
                   "--default_root_dir", str(tmp_path)] + extra_args)
    module = CausalLMModule(args, model, cfg)
    dm = UniversalDataModule(args=args, datasets={"train": ListDS()})
    state = Trainer(args).fit(module, dm)
    lines = [json.loads(l) for l in
             open(os.path.join(tmp_path, "metrics.jsonl"))]
    losses = [l["loss"] for l in lines if "loss" in l]
    return state, losses


def test_steps_per_execution_parity(mesh8, tmp_path):
    """--steps_per_execution K runs K optimizer steps per jitted
    dispatch (lax.scan over stacked batches) and must match the K=1
    run step for step: the rng fold_in(step) keeps substep dropout
    identical, so final params agree to float tolerance and the
    windowed loss logs are the per-window means of the K=1 losses."""
    state1, losses1 = _fit_tiny(tmp_path / "a", [])
    state2, losses2 = _fit_tiny(
        tmp_path / "b", ["--steps_per_execution", "2"])

    assert int(state1.step) == int(state2.step) == 4
    # spe=2 logs once per execution (steps 2 and 4), each the mean of
    # its two substeps
    assert len(losses1) == 4 and len(losses2) == 2
    np.testing.assert_allclose(
        losses2, [np.mean(losses1[:2]), np.mean(losses1[2:])],
        rtol=2e-5)
    flat1 = jax.tree_util.tree_leaves(state1.params)
    flat2 = jax.tree_util.tree_leaves(state2.params)
    for a, b in zip(flat1, flat2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-5, rtol=2e-4)


def test_steps_per_execution_resume_clamps_to_remaining(mesh8, tmp_path):
    """Resuming with fewer steps left than one K-group must shrink K to
    the remainder (finishing the schedule exactly), and resuming at or
    past the budget must run ZERO steps — the loop body only checks
    max_steps after an execution, so without the pre-loop guard a
    restored run overshoots the LR schedule by a whole group."""
    from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from fengshen_tpu.trainer import Trainer
    from fengshen_tpu.trainer.modules import CausalLMModule
    from fengshen_tpu.utils import UniversalCheckpoint

    cfg = LlamaConfig(vocab_size=64, hidden_size=16,
                      intermediate_size=32, num_hidden_layers=1,
                      num_attention_heads=2,
                      max_position_embeddings=32, dtype="float32")
    rng = np.random.RandomState(11)
    data = [{"input_ids": rng.randint(0, 63, 16).tolist()}
            for _ in range(64)]

    class ListDS:
        def __len__(self):
            return len(data)

        def __getitem__(self, i):
            return data[i]

    ckpt_dir = tmp_path / "ckpt"

    def fit(argv):
        args = _parse([
            "--train_batchsize", "4", "--learning_rate", "1e-3",
            "--warmup_steps", "1", "--log_every_n_steps", "1",
            "--every_n_train_steps", "3",
            "--save_ckpt_path", str(ckpt_dir),
            "--load_ckpt_path", str(ckpt_dir),
            "--default_root_dir", str(tmp_path)] + argv)
        trainer = Trainer(args)
        trainer.callbacks.append(UniversalCheckpoint(args))
        module = CausalLMModule(args, LlamaForCausalLM(cfg), cfg)
        dm = UniversalDataModule(args=args, datasets={"train": ListDS()})
        state = trainer.fit(module, dm)
        return trainer, state

    # leg 1: plain 3-step run, checkpoint lands at step 3
    t1, s1 = fit(["--max_steps", "3"])
    assert t1.global_step == 3 and int(s1.step) == 3

    # leg 2: resume at step 3 with budget 4 and K=5: K shrinks to the
    # single remaining step — exactly one more optimizer step, never
    # 4 or 5 more
    t2, s2 = fit(["--max_steps", "4", "--steps_per_execution", "5"])
    assert t2.global_step == 4 and int(s2.step) == 4

    # leg 3: resume at step 3 with K=2 and budget 3 (K-rounding would
    # push the effective budget BELOW the restored step): zero steps
    t3, s3 = fit(["--max_steps", "3", "--steps_per_execution", "2"])
    assert t3.global_step == 3 and int(s3.step) == 3

    # leg 4: resume at step 3 with budget 5 and K=2 — the remaining 2
    # steps are exactly one K-group, so the run must reach the full
    # budget. Double-rounding (align from step 0 before restore, then
    # re-align after) would trim 5->4 and finish a step short
    t4, s4 = fit(["--max_steps", "5", "--steps_per_execution", "2"])
    assert t4.global_step == 5 and int(s4.step) == 5


def test_grouped_prefetch_drops_partial_tail(capsys):
    from fengshen_tpu.trainer.trainer import _prefetch_grouped

    batches = [{"x": np.full((2,), i)} for i in range(5)]
    dev = jax.devices("cpu")[0]
    sh = jax.tree_util.tree_map(
        lambda _: jax.sharding.SingleDeviceSharding(dev), {"x": 0})
    out = list(_prefetch_grouped(iter(batches), sh["x"], 2))
    assert len(out) == 2
    group, stacked, _skips = out[0]
    assert len(group) == 2 and stacked["x"].shape == (2, 2)
    assert "dropping 1 tail batch" in capsys.readouterr().out


def test_grouped_prefetch_ragged_drops_but_loader_bugs_raise(capsys):
    """A ragged group (short final batch) drops loudly; a tree-structure
    mismatch (loader bug) must RAISE — swallowing it would turn a crash
    into a zero-step 'successful' run."""
    from fengshen_tpu.trainer.trainer import _prefetch_grouped

    dev = jax.devices("cpu")[0]
    sh = jax.sharding.SingleDeviceSharding(dev)

    # ragged shapes, same structure: dropped with the loud message
    ragged = [{"x": np.zeros((2,))}, {"x": np.zeros((3,))}]
    assert list(_prefetch_grouped(iter(ragged), {"x": sh}, 2)) == []
    assert "mismatched batch shapes" in capsys.readouterr().out

    # structure mismatch (missing key): surfaces, never swallowed
    bad = [{"x": np.zeros((2,))}, {"y": np.zeros((2,))}]
    with pytest.raises(ValueError):
        list(_prefetch_grouped(iter(bad), {"x": sh}, 2))


def test_every_n_checkpoint_fires_on_crossed_boundary():
    """Under steps_per_execution the global step jumps K at a time;
    every-n checkpointing must fire when a multiple of n falls INSIDE
    the execution span, not only on exact hits."""
    from fengshen_tpu.utils import UniversalCheckpoint

    class _T:
        pass

    cb = UniversalCheckpoint.__new__(UniversalCheckpoint)
    cb.every_n_train_steps = 8
    saved = []
    cb.save = lambda state, trainer, **kw: saved.append(
        trainer.global_step)

    t = _T()
    for prev, cur in [(0, 5), (5, 10), (10, 15), (15, 20), (20, 25)]:
        t.prev_global_step, t.global_step = prev, cur
        cb.on_train_step_end(t, state=None)
    # multiples of 8 (8, 16, 24) fall inside spans (5,10], (15,20],
    # (20,25] -> saves at global steps 10, 20, 25
    assert saved == [10, 20, 25]

    # K=1 semantics unchanged: exact-multiple steps save, others don't
    saved.clear()
    for cur in range(1, 17):
        t.prev_global_step, t.global_step = cur - 1, cur
        cb.on_train_step_end(t, state=None)
    assert saved == [8, 16]


def test_sigterm_preemption_saves_and_resumes(mesh8, tmp_path):
    """A REAL SIGTERM mid-fit (delivered by the fault-injection
    harness) saves a sync checkpoint at the next step boundary and
    exits cleanly; a fresh fit resumes from the saved global_step /
    consumed_samples and finishes the budget."""
    import signal

    from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from fengshen_tpu.resilience import FaultPlan
    from fengshen_tpu.trainer import Trainer
    from fengshen_tpu.trainer.modules import CausalLMModule
    from fengshen_tpu.utils import UniversalCheckpoint

    cfg = LlamaConfig(vocab_size=64, hidden_size=16,
                      intermediate_size=32, num_hidden_layers=1,
                      num_attention_heads=2,
                      max_position_embeddings=32, dtype="float32")
    rng = np.random.RandomState(3)
    data = [{"input_ids": rng.randint(0, 63, 16).tolist()}
            for _ in range(64)]

    class DS:
        def __len__(self):
            return 64

        def __getitem__(self, i):
            return data[i]

    ck = tmp_path / "ck"
    argv = ["--max_steps", "5", "--train_batchsize", "4",
            "--log_every_n_steps", "1", "--warmup_steps", "1",
            "--default_root_dir", str(tmp_path),
            "--save_ckpt_path", str(ck), "--load_ckpt_path", str(ck)]

    def run(plan=None):
        args = _parse(argv)
        trainer = Trainer(args)
        trainer.callbacks.append(UniversalCheckpoint(args))
        if plan is not None:
            plan.install(trainer)
        module = CausalLMModule(args, LlamaForCausalLM(cfg), cfg)
        dm = UniversalDataModule(args=args, datasets={"train": DS()})
        return trainer, trainer.fit(module, dm)

    prev = signal.getsignal(signal.SIGTERM)
    try:
        trainer1, state1 = run(FaultPlan(sigterm_at_step=2))
    finally:
        signal.signal(signal.SIGTERM, prev)
    assert trainer1._preempted
    assert trainer1.global_step == 2 and int(state1.step) == 2
    assert trainer1.consumed_samples == 8
    import orbax.checkpoint as ocp
    assert ocp.CheckpointManager(str(ck)).latest_step() == 2
    lines = [json.loads(l) for l in
             open(os.path.join(tmp_path, "metrics.jsonl"))]
    assert any(l.get("event") == "preempted_saved" and l["step"] == 2
               for l in lines)

    trainer2, state2 = run()
    assert trainer2.global_step == 5 and int(state2.step) == 5
    assert trainer2.consumed_samples == 20  # resumed at 8, not replayed


def test_grouped_prefetch_drops_ragged_group(capsys):
    """A loader's short final batch inside a full K-group must degrade
    (loud drop), not crash the run mid-epoch."""
    from fengshen_tpu.trainer.trainer import _prefetch_grouped

    batches = [{"x": np.zeros((2,))}, {"x": np.zeros((2,))},
               {"x": np.zeros((2,))}, {"x": np.zeros((1,))}]  # ragged
    dev = jax.devices("cpu")[0]
    sh = jax.sharding.SingleDeviceSharding(dev)
    out = list(_prefetch_grouped(iter(batches), sh, 2))
    assert len(out) == 1  # first group ok, ragged second group dropped
    assert "mismatched batch shapes" in capsys.readouterr().out


def test_aot_cache_dir_reuses_train_step_across_fits(mesh8, tmp_path):
    """--aot_cache_dir (docs/aot_cache.md): the first fit compiles the
    train step and persists it; a second fit — the restart/rewind case
    — deserializes it (cache hit) and trains identically: same losses,
    same final params."""
    from fengshen_tpu.observability import get_registry

    def _hits():
        m = get_registry().get("fstpu_aot_cache_hits_total")
        return {k[0]: c.value for k, c in m.children()} if m else {}

    cache_dir = tmp_path / "aot-cache"
    state1, losses1 = _fit_tiny(
        tmp_path / "a", ["--aot_cache_dir", str(cache_dir)])
    blobs = [f for f in os.listdir(cache_dir) if f.endswith(".aotx")]
    assert any(f.startswith("trainer-train_step") for f in blobs), blobs
    base = _hits().get("trainer/train_step", 0)

    state2, losses2 = _fit_tiny(
        tmp_path / "b", ["--aot_cache_dir", str(cache_dir)])
    assert _hits().get("trainer/train_step", 0) > base
    assert int(state1.step) == int(state2.step) == 4
    np.testing.assert_allclose(losses1, losses2, rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(state1.params),
                    jax.tree_util.tree_leaves(state2.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)
