"""T5 (Randeng) golden-value parity vs HF torch."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fengshen_tpu.models.t5 import T5Config, T5ForConditionalGeneration
from fengshen_tpu.models.t5.convert import torch_to_params


def _make_pair(tie=True, gated=False):
    torch = pytest.importorskip("torch")
    import transformers
    hf_cfg = transformers.T5Config(
        vocab_size=128, d_model=32, d_kv=8, d_ff=64, num_layers=2,
        num_heads=4, tie_word_embeddings=tie,
        feed_forward_proj="gated-gelu" if gated else "relu",
        attn_implementation="eager")
    torch.manual_seed(0)
    tm = transformers.T5ForConditionalGeneration(hf_cfg).eval()
    cfg = T5Config(vocab_size=128, d_model=32, d_kv=8, d_ff=64,
                   num_layers=2, num_heads=4, tie_word_embeddings=tie,
                   feed_forward_proj="gated-gelu" if gated else "relu",
                   dtype="float32")
    return torch_to_params(tm.state_dict(), cfg), tm, cfg


def test_t5_forward_parity():
    import torch
    params, tm, cfg = _make_pair()
    enc_ids = np.array([[3, 17, 9, 42, 7, 1]], dtype=np.int32)
    dec_ids = np.array([[0, 5, 11, 2]], dtype=np.int32)
    mask = np.array([[1, 1, 1, 1, 0, 0]], dtype=np.int32)
    logits = T5ForConditionalGeneration(cfg).apply(
        {"params": params}, jnp.asarray(enc_ids), jnp.asarray(dec_ids),
        attention_mask=jnp.asarray(mask))
    with torch.no_grad():
        ref = tm(input_ids=torch.tensor(enc_ids, dtype=torch.long),
                 attention_mask=torch.tensor(mask, dtype=torch.long),
                 decoder_input_ids=torch.tensor(dec_ids, dtype=torch.long)
                 ).logits.numpy()
    np.testing.assert_allclose(np.asarray(logits), ref, atol=2e-3)


def test_t5_gated_untied_parity():
    import torch
    params, tm, cfg = _make_pair(tie=False, gated=True)
    enc_ids = np.array([[3, 17, 9, 42]], dtype=np.int32)
    dec_ids = np.array([[0, 5]], dtype=np.int32)
    logits = T5ForConditionalGeneration(cfg).apply(
        {"params": params}, jnp.asarray(enc_ids), jnp.asarray(dec_ids))
    with torch.no_grad():
        ref = tm(input_ids=torch.tensor(enc_ids, dtype=torch.long),
                 decoder_input_ids=torch.tensor(dec_ids, dtype=torch.long)
                 ).logits.numpy()
    np.testing.assert_allclose(np.asarray(logits), ref, atol=2e-3)


def test_t5_sharded_matches_replicated(mesh8):
    params, _, cfg = _make_pair()
    model = T5ForConditionalGeneration(cfg)
    enc = jnp.asarray(np.random.RandomState(0).randint(0, 127, (4, 8)),
                      jnp.int32)
    dec = jnp.asarray(np.random.RandomState(1).randint(0, 127, (4, 4)),
                      jnp.int32)
    ref = model.apply({"params": params}, enc, dec)
    from fengshen_tpu.parallel import make_shardings
    shardings = make_shardings(model.partition_rules(), params, mesh8)
    sharded = jax.device_put(params, shardings)
    out = jax.jit(lambda p, e, d: model.apply({"params": p}, e, d))(
        sharded, enc, dec)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4)


def test_t5_decoder_causality():
    params, _, cfg = _make_pair()
    model = T5ForConditionalGeneration(cfg)
    enc = jnp.asarray([[3, 17, 9, 42]], jnp.int32)
    dec = jnp.asarray([[0, 5, 11, 2]], jnp.int32)
    ref = model.apply({"params": params}, enc, dec)
    dec2 = dec.at[0, -1].set(99)
    out = model.apply({"params": params}, enc, dec2)
    np.testing.assert_allclose(np.asarray(out[:, :-1]),
                               np.asarray(ref[:, :-1]), atol=1e-5)
