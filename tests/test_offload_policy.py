"""Memory-placement subsystem (docs/offload.md): capability probe +
offload policy + AOT-key isolation + /metrics gauges.

Fast lane, model-free by design (ISSUE 9 satellite): everything here is
probe plumbing and placement math — the multi-layer parity fits live in
tests/test_trainer.py (slow lane).
"""

import dataclasses

import jax
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fengshen_tpu.trainer import memory as mem
from fengshen_tpu.trainer.memory import (HOST_MEMORY_KINDS,
                                         OFFLOAD_LEVELS,
                                         MemoryCapabilities,
                                         probe_memory_capabilities,
                                         record_offload_metrics,
                                         resolve_offload_policy)


def _fake_caps(pinned=True, unpinned=True, device_bytes=None,
               host_bytes=None, device_count=4,
               device_memory_kind="device"):
    return MemoryCapabilities(
        backend="fake", device_count=device_count,
        supported={"pinned_host": pinned, "unpinned_host": unpinned},
        device_memory_kind=device_memory_kind,
        device_bytes=device_bytes, host_bytes=host_bytes)


# ---- the probe ------------------------------------------------------


def test_probe_reports_this_backends_kinds():
    caps = probe_memory_capabilities()
    assert caps.backend == "cpu"  # conftest pins the CPU mesh
    # this jax build's CPU backend: only unpinned_host exists, and it
    # is ALSO the device default (NOTES.md) — the exact environment
    # that made the hard-coded pinned_host offload raise since seed
    assert caps.supported["unpinned_host"] is True
    assert caps.supported["pinned_host"] is False
    assert caps.host_kind == "unpinned_host"
    assert caps.device_memory_kind == "unpinned_host"
    assert caps.device_bytes is None  # CPU reports no budget
    assert caps.host_bytes and caps.host_bytes > 0


def test_probe_is_cached_per_process(monkeypatch):
    calls = []
    real = mem._kind_supported

    def counting(kind, device):
        calls.append(kind)
        return real(kind, device)

    monkeypatch.setattr(mem, "_kind_supported", counting)
    first = probe_memory_capabilities(refresh=True)
    assert sorted(calls) == sorted(HOST_MEMORY_KINDS)
    again = probe_memory_capabilities()
    assert again is first
    assert len(calls) == len(HOST_MEMORY_KINDS)  # no re-probe


# ---- placement math (pure, fake capabilities) -----------------------


def test_auto_level_ladder_against_device_budget():
    gib = 1 << 30
    caps = _fake_caps(device_bytes=16 * gib, host_bytes=256 * gib)
    # params+grads+moments fit -> none
    p = resolve_offload_policy("auto", params_bytes=4 * gib,
                               opt_bytes=8 * gib, caps=caps)
    assert p.level == "none" and not p.offloads_opt_state
    # moments overflow -> opt
    p = resolve_offload_policy("auto", params_bytes=20 * gib,
                               opt_bytes=40 * gib, caps=caps)
    assert p.level == "opt" and p.opt_state_kind == "pinned_host"
    assert p.master_kind is None
    # params+grads overflow: the PER-STEP peak no longer fits, and
    # opt_master only lowers between-step residency — streaming is the
    # only level that bounds the peak, so auto goes straight there
    p = resolve_offload_policy("auto", params_bytes=40 * gib,
                               opt_bytes=80 * gib, caps=caps)
    assert p.level == "stream"
    # ...unless the entry point cannot stream (the standard Trainer):
    # opt_master is the best-effort deepest level, said so loudly
    p = resolve_offload_policy("auto", params_bytes=40 * gib,
                               opt_bytes=80 * gib, caps=caps,
                               can_stream=False)
    assert p.level == "opt_master"
    assert p.master_kind == "pinned_host"
    assert "best effort" in p.reason


def test_auto_budget_counts_only_state_sharding_ways():
    """Replication awareness: a pure-DP mesh replicates the state per
    replica, so capacity is device_bytes x (fsdp*tensor*pipe), NOT
    x device_count — counting every device would resolve 'none' on
    shapes that OOM."""
    gib = 1 << 30
    caps = _fake_caps(device_bytes=1 * gib, device_count=8)
    # 8-way sharded state (the default when no mesh info): 3 GiB of
    # params+grads+moments fit the 7.2 GiB budget
    p = resolve_offload_policy("auto", params_bytes=1 * gib,
                               opt_bytes=1 * gib, caps=caps)
    assert p.level == "none"
    # the SAME bytes on a pure-DP mesh (1-way sharded replica): only
    # 0.9 GiB of budget per replica — moments must offload
    p = resolve_offload_policy("auto", params_bytes=256 << 20,
                               opt_bytes=512 << 20, caps=caps,
                               state_shard_ways=1)
    assert p.level == "opt"
    # shard ways are clamped to the device count (a misreported mesh
    # must not inflate the budget past the hardware)
    p = resolve_offload_policy("auto", params_bytes=16 * gib,
                               opt_bytes=32 * gib, caps=caps,
                               state_shard_ways=1000)
    assert p.level != "none"


def test_auto_moments_only_overflow_without_host_kind():
    """When only the moments overflow and the backend has no host
    memory kind, 'opt' cannot help: a streaming-capable caller
    streams, a non-streaming one runs without offload (said loudly) —
    never a reason line claiming params+grads overflowed."""
    gib = 1 << 30
    caps = _fake_caps(pinned=False, unpinned=False,
                      device_bytes=1 * gib, device_count=4)
    # params+grads (2 GiB) fit the 3.6 GiB budget; moments (4 GiB) don't
    p = resolve_offload_policy("auto", params_bytes=1 * gib,
                               opt_bytes=4 * gib, caps=caps)
    assert p.level == "stream"
    assert "moments" in p.reason and "params+grads" not in p.reason
    p = resolve_offload_policy("auto", params_bytes=1 * gib,
                               opt_bytes=4 * gib, caps=caps,
                               can_stream=False)
    assert p.level == "none"
    assert "may OOM" in p.reason


def test_auto_without_budget_info_picks_none():
    p = resolve_offload_policy("auto", params_bytes=1 << 40,
                               opt_bytes=1 << 41,
                               caps=_fake_caps(device_bytes=None))
    assert p.level == "none"
    assert "budget" in p.reason


def test_fallback_ladder_without_pinned_host():
    caps = _fake_caps(pinned=False)
    p = resolve_offload_policy("opt", caps=caps)
    assert p.level == "opt"
    assert p.opt_state_kind == "unpinned_host"  # one rung down, loudly
    p = resolve_offload_policy("opt_master", caps=caps)
    assert (p.opt_state_kind, p.master_kind) == \
        ("unpinned_host", "unpinned_host")


def test_fallback_to_none_without_any_host_kind():
    caps = _fake_caps(pinned=False, unpinned=False)
    for request in ("opt", "opt_master"):
        p = resolve_offload_policy(request, caps=caps)
        assert p.level == "none", request
        assert p.opt_state_kind is None
        assert "no host memory kind" in p.reason
    # "stream" is exempt: the streamed engine parks state as host
    # NUMPY (trainer/param_streaming.py) and needs no jax memory kind,
    # so its level — and its moments_dtype knob — survive
    p = resolve_offload_policy("stream", caps=caps,
                               moments_dtype="bfloat16")
    assert p.level == "stream"
    assert p.moments_dtype == "bfloat16"
    # auto with a blown budget: opt can't help (no kind to park into),
    # so a streaming-capable entry point streams...
    tight = dataclasses.replace(caps, device_bytes=1 << 30)
    p = resolve_offload_policy("auto", params_bytes=1 << 40,
                               opt_bytes=1 << 40, caps=tight)
    assert p.level == "stream"
    # ...and a non-streaming one degrades to none rather than planning
    # jax-sharding placements against nothing
    p = resolve_offload_policy("auto", params_bytes=1 << 40,
                               opt_bytes=1 << 40, caps=tight,
                               can_stream=False)
    assert p.level == "none"


def test_stream_demotes_when_entry_point_cannot_stream():
    p = resolve_offload_policy("stream", caps=_fake_caps(),
                               can_stream=False)
    assert p.level == "opt_master"
    assert "stream" in p.reason


def test_explicit_memory_kind_override():
    # forcing a supported kind wins over the probe's preference
    p = resolve_offload_policy("opt", caps=_fake_caps(),
                               memory_kind="unpinned_host")
    assert p.opt_state_kind == "unpinned_host"
    # forcing an unsupported kind raises — never a silent degrade
    with pytest.raises(ValueError, match="offload_memory_kind"):
        resolve_offload_policy("opt", caps=_fake_caps(pinned=False),
                               memory_kind="pinned_host")
    with pytest.raises(ValueError, match="unknown"):
        resolve_offload_policy("opt", caps=_fake_caps(),
                               memory_kind="nvme")
    with pytest.raises(ValueError, match="unknown offload request"):
        resolve_offload_policy("zero3", caps=_fake_caps())


def test_stream_moments_dtype_is_a_policy_knob():
    gib = 1 << 30
    caps = _fake_caps(host_bytes=64 * gib)
    # fp32 moments dwarf host RAM -> bf16 storage suggested
    p = resolve_offload_policy("stream", params_bytes=26 * gib,
                               opt_bytes=104 * gib, caps=caps)
    assert p.moments_dtype == "bfloat16"
    # plenty of host RAM -> param-dtype bit-parity default
    p = resolve_offload_policy("stream", params_bytes=1 * gib,
                               opt_bytes=2 * gib, caps=caps)
    assert p.moments_dtype is None
    # an explicit dtype always wins
    p = resolve_offload_policy("stream", params_bytes=26 * gib,
                               opt_bytes=104 * gib, caps=caps,
                               moments_dtype="float32")
    assert p.moments_dtype == "float32"
    # "param" is the explicit bit-parity demand: NEVER auto-upgraded,
    # even when fp32 moments dwarf host RAM (the streamed drivers'
    # --offload_moments_dtype=param contract)
    p = resolve_offload_policy("stream", params_bytes=26 * gib,
                               opt_bytes=104 * gib, caps=caps,
                               moments_dtype="param")
    assert p.moments_dtype is None
    assert "bfloat16" not in p.reason


def test_policy_fingerprints_distinct_per_placement():
    caps = _fake_caps()
    fps = {resolve_offload_policy(lvl, caps=caps).fingerprint()
           for lvl in OFFLOAD_LEVELS}
    assert len(fps) == len(OFFLOAD_LEVELS)
    # the probed kind set enters the fingerprint too: the same level
    # on a pinned-less backend is a different placement
    assert resolve_offload_policy("opt", caps=caps).fingerprint() != \
        resolve_offload_policy(
            "opt", caps=_fake_caps(pinned=False)).fingerprint()


def test_announce_logs_the_placement_and_why():
    entries = []
    p = resolve_offload_policy("opt", caps=_fake_caps(pinned=False),
                               log=entries.append)
    assert entries and entries[0]["event"] == "offload_policy"
    assert entries[0]["level"] == p.level
    assert entries[0]["opt_state_kind"] == "unpinned_host"
    assert entries[0]["reason"]


# ---- TrainState wiring ----------------------------------------------


def _tiny_sharding_state(mesh):
    from fengshen_tpu.trainer.train_state import TrainState
    sh = NamedSharding(mesh, P())
    return TrainState(step=sh, params={"w": sh}, opt_state={"mu": sh},
                      apply_fn=lambda *a, **k: None, tx=optax.sgd(1e-3),
                      bad_step_count=sh)


def test_offload_opt_state_shardings_no_longer_raises():
    """THE seed failure (ROADMAP item 3): the default call resolved
    pinned_host unconditionally and raised at sharding construction on
    this backend. It now probes."""
    from fengshen_tpu.trainer.train_state import \
        offload_opt_state_shardings
    mesh = Mesh(np.array(jax.devices()).reshape(-1), ("data",))
    out = offload_opt_state_shardings(_tiny_sharding_state(mesh))
    kind = probe_memory_capabilities().host_kind
    assert out.opt_state["mu"].memory_kind == kind
    assert out.params["w"].memory_kind != "pinned_host"


def test_offload_opt_state_shardings_rejects_unsupported_kind():
    from fengshen_tpu.trainer.train_state import \
        offload_opt_state_shardings
    mesh = Mesh(np.array(jax.devices()).reshape(-1), ("data",))
    with pytest.raises(ValueError, match="pinned_host"):
        offload_opt_state_shardings(_tiny_sharding_state(mesh),
                                    memory_kind="pinned_host")


def test_offload_request_from_args_flag_precedence():
    import argparse
    from fengshen_tpu.trainer.memory import offload_request_from_args
    ns = argparse.Namespace(offload="auto", offload_optimizer=False)
    assert offload_request_from_args(ns) == "auto"
    ns.offload_optimizer = True  # legacy bool maps to opt...
    assert offload_request_from_args(ns) == "opt"
    ns.offload = "none"          # ...but an explicit --offload wins
    assert offload_request_from_args(ns) == "none"


# ---- placement in the AOT cache key ---------------------------------


def test_offload_placement_forces_distinct_aot_keys(tmp_path):
    """Acceptance (ISSUE 9): changing the offload level forces a
    distinct cache key, and both placements' payloads coexist in ONE
    cache dir without cross-hits."""
    from fengshen_tpu.aot import AotConfig, AotSetup, cache_key
    from fengshen_tpu.observability import MetricsRegistry

    fp_a = resolve_offload_policy("none", caps=_fake_caps()).fingerprint()
    fp_b = resolve_offload_policy("opt", caps=_fake_caps()).fingerprint()
    jitted = jax.jit(lambda x: x * 2)
    lowered = jitted.lower(jax.ShapeDtypeStruct((4,), np.float32))
    base = cache_key("t/step", lowered)
    assert cache_key("t/step", lowered, extra=fp_a) != \
        cache_key("t/step", lowered, extra=fp_b)
    # empty extra keeps the pre-placement key derivation (no blanket
    # cache invalidation for non-trainer users)
    assert cache_key("t/step", lowered, extra="") == base

    setup = AotSetup(AotConfig(cache_dir=str(tmp_path), record=False),
                     registry=MetricsRegistry())
    aval = jax.ShapeDtypeStruct((4,), np.float32)
    setup.wrap(lambda x: x * 2, "t/step", key_extra=fp_a).warm(aval)
    setup.wrap(lambda x: x * 2, "t/step", key_extra=fp_b).warm(aval)
    blobs = setup.cache.entries()
    assert len(blobs) == 2  # same fn, same aval, two placements
    assert len({e.key for e in blobs}) == 2

    # a fresh process at placement A hits ONLY its own entry
    reg = MetricsRegistry()
    setup2 = AotSetup(AotConfig(cache_dir=str(tmp_path), record=False),
                      registry=reg)
    setup2.wrap(lambda x: x * 2, "t/step", key_extra=fp_a).warm(aval)
    from fengshen_tpu.aot import HITS_METRIC, MISSES_METRIC
    assert reg.get(HITS_METRIC).labels("t/step").value == 1
    assert reg.get(MISSES_METRIC) is None or \
        reg.get(MISSES_METRIC).labels("t/step").value == 0
    assert len(setup2.cache.entries()) == 2  # nothing clobbered


# ---- /metrics gauges ------------------------------------------------


def test_offload_gauges_pinned_exposition():
    """Pinned /metrics check (ISSUE 9 satellite): the exact exposition
    lines the new gauges render."""
    from fengshen_tpu.observability import (MetricsRegistry,
                                            render_prometheus)
    policy = resolve_offload_policy("opt", caps=_fake_caps(pinned=False))
    reg = MetricsRegistry()
    record_offload_metrics(policy, host_resident_bytes=4096,
                           registry=reg)
    text = render_prometheus(reg)
    assert 'fstpu_memory_kind_supported{kind="pinned_host"} 0' in text
    assert 'fstpu_memory_kind_supported{kind="unpinned_host"} 1' in text
    assert "fstpu_offload_host_bytes 4096" in text
    assert "fstpu_offload_level 1" in text  # opt = ladder index 1


def test_offload_gauge_level_indices_cover_the_ladder():
    from fengshen_tpu.observability import MetricsRegistry
    for i, lvl in enumerate(OFFLOAD_LEVELS):
        reg = MetricsRegistry()
        record_offload_metrics(
            resolve_offload_policy(lvl, caps=_fake_caps()), registry=reg)
        assert reg.get("fstpu_offload_level").value() == float(i)
