"""Streaming tier (ISSUE 20, docs/streaming.md): sampled speculative
serving delivered token-by-token over SSE with resume-from-token-k.

The load-bearing contracts:

- greedy STREAMED output is token-identical to the batch path on both
  KV layouts, with the one-decode-compile pin intact (streaming is
  delivery-only — it must never touch the decode graph);
- sampled decode with a pinned per-lane seed is reproducible
  run-to-run (same seed ⇒ byte-identical stream, twice; different
  seed ⇒ different stream), because the lane key derives from
  `(engine seed, request seed)` — never from placement or co-tenancy;
- the self-draft tower (draft layers sharing the target's embedding)
  verifies greedy token-identical to non-spec, keeps ONE decode
  compile, and beats prompt-lookup's committed/forward on
  non-repetitive traffic;
- a spec engine accepts `resume_tokens` (resume-from-token-k) and the
  resumed continuation is token-identical to the uninterrupted run;
- the SSE wire format round-trips; `Last-Event-ID` reconnect replays
  from token k+1 on the stdlib api path;
- the fleet router's streaming proxy survives a replica death
  mid-stream with a GAPLESS token-identical concatenated stream
  (journal resume + dedupe cursor), and follows an `evacuated`
  terminal event to the adopter transparently;
- `/stats` grows `streams_active` only after the first streamed
  request (never-streamed engines stay byte-shape-identical).
"""

import json
import os
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from fengshen_tpu.serving import ContinuousBatchingEngine, EngineConfig
from fengshen_tpu.streaming import (StreamBook, TokenStream,
                                    format_event, iter_sse)
from fengshen_tpu.utils.generate import generate

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

PAGED = dict(kv_layout="paged", kv_block_size=16)


@pytest.fixture(scope="module")
def tiny():
    cfg = LlamaConfig(vocab_size=97, hidden_size=32,
                      intermediate_size=64, num_hidden_layers=2,
                      num_attention_heads=4,
                      max_position_embeddings=64, dtype="float32")
    model = LlamaForCausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 4), jnp.int32))["params"]
    return model, params


def _prompts(lengths, seed=0):
    rng = np.random.RandomState(seed)
    return [rng.randint(3, 96, n).astype(np.int32) for n in lengths]


def _ref(model, params, prompt, max_new):
    out = np.asarray(generate(model, params, jnp.asarray(prompt)[None],
                              max_new_tokens=max_new,
                              eos_token_id=None, pad_token_id=0))
    return out[0, len(prompt):].tolist()


def _stream_events(engine, prompts, seeds=None, **submit_kw):
    """Submit every prompt with stream=True, drain the engine, return
    each request's full event list."""
    reqs = []
    for i, p in enumerate(prompts):
        kw = dict(submit_kw)
        if seeds is not None:
            kw["seed"] = seeds[i]
        reqs.append(engine.submit(p, stream=True,
                                  request_id=f"sr{i}", **kw))
    streams = [engine.streams.get(r.request_id) for r in reqs]
    engine.run_until_idle()
    return [list(s.events(0, timeout=30.0)) for s in streams]


def _tokens_of(events):
    assert events[-1][0] == "done", events[-1]
    return [t for (kind, _i, t) in events if kind == "token"]


# ---- SSE wire format ----------------------------------------------------

def test_sse_roundtrip():
    frames = (format_event("token", {"token": 42}, event_id=0) +
              format_event("token", {"token": 7}, event_id=1) +
              format_event("done", {"finish_reason": "length"},
                           event_id=2))
    evs = list(iter_sse(frames.decode().splitlines()))
    assert [(e["event"], e["id"]) for e in evs] == \
        [("token", 0), ("token", 1), ("done", 2)]
    assert evs[0]["data"] == {"token": 42}
    assert evs[2]["data"] == {"finish_reason": "length"}


def test_iter_sse_tolerates_comments_and_split_data():
    raw = (": keep-alive\n\n"
           "id: 3\nevent: token\ndata: {\"to\ndata: ken\": 1}\n\n")
    evs = list(iter_sse(raw.splitlines()))
    assert evs == [{"event": "token", "id": 3, "data": {"token": 1}}]


def test_token_stream_replay_and_terminal():
    s = TokenStream()
    s.publish([5, 6])
    s.publish([5, 6, 7], finish_reason="length")
    evs = list(s.events(0, timeout=1.0))
    assert evs == [("token", 0, 5), ("token", 1, 6), ("token", 2, 7),
                   ("done", 3, "length")]
    # replay from k: the Last-Event-ID contract
    assert list(s.events(2, timeout=1.0)) == [
        ("token", 2, 7), ("done", 3, "length")]


# ---- greedy streamed == batch, both layouts, one compile ----------------

@pytest.mark.parametrize("layout_kw", [{}, PAGED],
                         ids=["slot", "paged"])
def test_greedy_streamed_token_identical(tiny, layout_kw):
    model, params = tiny
    prompts = _prompts((5, 11, 7))
    refs = [_ref(model, params, p, 8) for p in prompts]
    engine = ContinuousBatchingEngine(model, params, EngineConfig(
        num_slots=2, buckets=(8, 16), max_new_tokens=8, max_queue=16,
        **layout_kw))
    events = _stream_events(engine, prompts)
    assert [_tokens_of(e) for e in events] == refs
    # event ids are the token indices, contiguous from 0
    for evs in events:
        assert [i for (k, i, _t) in evs if k == "token"] == \
            list(range(8))
    # streaming is delivery-only: the decode graph compiled ONCE
    assert engine._decode_jit._cache_size() == 1


# ---- pinned-seed sampled reproducibility --------------------------------

def test_sampled_stream_pinned_seed_reproducible(tiny):
    model, params = tiny
    prompts = _prompts((5, 11, 7))

    def run(seed0):
        engine = ContinuousBatchingEngine(model, params, EngineConfig(
            num_slots=2, buckets=(8, 16), max_new_tokens=8,
            max_queue=16, do_sample=True, temperature=0.9, top_k=20))
        return _stream_events(engine, prompts,
                              seeds=[seed0 + i for i in range(3)])

    a, b, c = run(7), run(7), run(11)
    # same pinned seed ⇒ byte-identical event streams, twice
    assert a == b
    assert [_tokens_of(e) for e in a] != [_tokens_of(e) for e in c]


def test_sampled_seed_default_derives_from_request_id(tiny):
    """No explicit seed: the lane key folds from the request id, so a
    retry under the SAME id reproduces the same stream — the fleet
    router's resubmit-and-dedupe path depends on this."""
    model, params = tiny
    prompt = _prompts((9,))[0]

    def run():
        engine = ContinuousBatchingEngine(model, params, EngineConfig(
            num_slots=2, buckets=(8, 16), max_new_tokens=8,
            max_queue=16, do_sample=True, temperature=0.9, top_k=20))
        req = engine.submit(prompt, request_id="pinned-id")
        engine.run_until_idle()
        return req.tokens

    assert run() == run()


# ---- self-draft tower ---------------------------------------------------

def test_self_draft_greedy_parity_one_compile(tiny):
    model, params = tiny
    prompts = _prompts((5, 11, 7))
    refs = [_ref(model, params, p, 8) for p in prompts]
    for layout_kw in ({}, PAGED):
        engine = ContinuousBatchingEngine(model, params, EngineConfig(
            num_slots=2, buckets=(8, 16), max_new_tokens=8,
            max_queue=16, spec_mode="self_draft", spec_gamma=4,
            spec_draft_layers=1, **layout_kw))
        assert engine.generate_all(prompts) == refs
        assert engine._decode_jit._cache_size() == 1


def test_self_draft_sampled_pinned_seed_reproducible(tiny):
    model, params = tiny
    prompts = _prompts((5, 11))

    def run():
        engine = ContinuousBatchingEngine(model, params, EngineConfig(
            num_slots=2, buckets=(8, 16), max_new_tokens=8,
            max_queue=16, spec_mode="self_draft", spec_gamma=4,
            spec_draft_layers=1, do_sample=True, temperature=0.9,
            top_k=20))
        return _stream_events(engine, prompts, seeds=[3, 4])

    assert run() == run()


def test_self_draft_beats_lookup_on_nonrepetitive(tiny):
    """The tentpole's acceptance direction: on uniform-random prompts
    (nothing for the ngram copy to find) the draft tower's acceptance
    must exceed prompt-lookup's on identical traffic."""
    model, params = tiny
    prompts = _prompts((16, 16, 16, 16), seed=3)

    def acceptance(mode, **extra):
        engine = ContinuousBatchingEngine(model, params, EngineConfig(
            num_slots=2, buckets=(16, 24), max_new_tokens=12,
            max_queue=8, spec_mode=mode, spec_gamma=4, **extra))
        engine.generate_all(prompts)
        return engine.stats()["spec_acceptance_rate"]

    assert acceptance("self_draft", spec_draft_layers=1) > \
        acceptance("prompt_lookup")


def test_spec_resume_token_identical(tiny):
    """Resume-from-token-k on a SPEC engine (the restriction this PR
    lifts): prefix from the journal + spec continuation must equal the
    uninterrupted spec run."""
    model, params = tiny
    prompt = _prompts((9,))[0]
    for mode, extra in (("prompt_lookup", {}),
                        ("self_draft", {"spec_draft_layers": 1})):
        cfg = dict(num_slots=2, buckets=(8, 16), max_new_tokens=10,
                   max_queue=8, spec_mode=mode, spec_gamma=4, **extra)
        e1 = ContinuousBatchingEngine(model, params,
                                      EngineConfig(**cfg))
        full = e1.generate_all([prompt])[0]
        e2 = ContinuousBatchingEngine(model, params,
                                      EngineConfig(**cfg))
        req = e2.submit(prompt, resume_tokens=full[:4],
                        resume_source="test")
        e2.run_until_idle()
        assert req.tokens == full, (mode, req.tokens, full)


# ---- /stats shape gating ------------------------------------------------

def test_stats_streams_key_gating(tiny):
    model, params = tiny
    prompts = _prompts((5,))
    cfg = EngineConfig(num_slots=2, buckets=(8,), max_new_tokens=4,
                       max_queue=8)
    plain = ContinuousBatchingEngine(model, params, cfg)
    plain.generate_all(prompts)
    assert "streams_active" not in plain.stats()

    streamed = ContinuousBatchingEngine(model, params, cfg)
    _stream_events(streamed, prompts)
    st = streamed.stats()
    assert st["streams_active"] == 0
    # only EXTENDS: every non-stream key the plain engine reports is
    # still present under the same name
    assert set(plain.stats()) <= set(st)


# ---- stdlib api path: SSE route + Last-Event-ID reconnect ---------------

class _IntTokenizer:
    eos_token_id = None
    pad_token_id = 0

    def encode(self, text):
        return [int(t) for t in text.split()]

    def decode(self, ids):
        return " ".join(str(int(t)) for t in ids)


def _sse_post(base, payload, headers=None, timeout=60):
    req = urllib.request.Request(
        f"{base}/api/text_generation/stream",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json",
                 **(headers or {})})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        assert r.headers.get("Content-Type") == "text/event-stream"
        return list(iter_sse(r))


def test_stdlib_sse_route_and_reconnect(tiny):
    from fengshen_tpu.api.main import (PipelineConfig, ServerConfig,
                                       build_stdlib_server,
                                       start_continuous_engine)
    from fengshen_tpu.pipelines.text_generation import Pipeline

    model, params = tiny
    pipe = Pipeline(module=model, params=params,
                    tokenizer=_IntTokenizer(), max_new_tokens=6,
                    eos_token_id=None, pad_token_id=0)
    engine = start_continuous_engine(
        pipe, {"num_slots": 2, "buckets": (8,), "max_queue": 8})
    server = build_stdlib_server(
        ServerConfig(host="127.0.0.1", port=0, engine="continuous"),
        PipelineConfig(task="text_generation"), pipeline=pipe,
        engine=engine)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{port}"
    try:
        # the non-streamed answer is the reference
        req = urllib.request.Request(
            f"{base}/api/text_generation",
            data=json.dumps({"input_text": "5 7 9",
                             "request_id": "batch-1"}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as r:
            ref = json.loads(r.read())["result"]

        evs = _sse_post(base, {"input_text": "5 7 9",
                               "request_id": "sse-1"})
        toks = [e["data"]["token"] for e in evs
                if e["event"] == "token"]
        ids = [e["id"] for e in evs if e["event"] == "token"]
        assert ids == list(range(6))
        assert evs[-1]["event"] == "done"
        assert evs[-1]["data"]["result"] == ref
        assert " ".join(str(t) for t in toks) == ref

        # Last-Event-ID reconnect (header path): replay from k+1
        evs2 = _sse_post(base, {"request_id": "sse-1"},
                         headers={"Last-Event-ID": "2"})
        assert [e["id"] for e in evs2 if e["event"] == "token"] == \
            [3, 4, 5]
        assert [e["data"]["token"] for e in evs2
                if e["event"] == "token"] == toks[3:]
        assert evs2[-1]["event"] == "done"

        # body-field reconnect is the same contract
        evs3 = _sse_post(base, {"request_id": "sse-1",
                                "last_event_id": 4})
        assert [e["id"] for e in evs3 if e["event"] == "token"] == [5]

        # unknown id reconnect: 404 before any stream byte
        with pytest.raises(urllib.error.HTTPError) as exc:
            _sse_post(base, {"request_id": "nope",
                             "last_event_id": 0})
        assert exc.value.code == 404

        # fresh submission without input_text: 422
        with pytest.raises(urllib.error.HTTPError) as exc:
            _sse_post(base, {"max_new_tokens": 3})
        assert exc.value.code == 422

        # reproducibility across the wire: same explicit seed twice
        s1 = _sse_post(base, {"input_text": "5 7 9", "seed": 13,
                              "request_id": "sse-s1"})
        s2 = _sse_post(base, {"input_text": "5 7 9", "seed": 13,
                              "request_id": "sse-s2"})
        assert ([e["data"] for e in s1 if e["event"] == "token"] ==
                [e["data"] for e in s2 if e["event"] == "token"])
    finally:
        server.shutdown()
        engine.stop()


# ---- fleet router: kill mid-stream, gapless resume ----------------------

class _ManualClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


class _DyingStreamTransport:
    """Replica a:1 streams `die_after` tokens then dies mid-stream
    (maybe-executed); its committed prefix of `journal_len` tokens is
    journaled fleet-wide; b:2 serves the resumed request to the end,
    REPLAYING from token 0 like a real engine stream does."""

    def __init__(self, n_tokens=8, die_after=3, journal_len=5):
        from fengshen_tpu.fleet import TransportError
        self._err = TransportError
        self.n, self.die, self.jlen = n_tokens, die_after, journal_len
        self.bodies = []

    @staticmethod
    def _tok(i):
        return 100 + i

    def request(self, base_url, method, path, body, timeout_s):
        name = base_url.split("://", 1)[1]
        if path == "/healthz":
            return 200, {"ready": True}
        if path == "/stats":
            return 200, {"slots_active": 0, "queue_depth": 0,
                         "num_slots": 4, "draining": False}
        if path.startswith("/partial/"):
            if name == "b:2":
                return 200, {"state": "running",
                             "tokens": [self._tok(i)
                                        for i in range(self.jlen)]}
            raise self._err("dead", sent=False)
        return 404, {}

    def stream(self, base_url, method, path, body, timeout_s):
        name = base_url.split("://", 1)[1]
        self.bodies.append((name, dict(body)))
        if name == "a:1":
            for i in range(self.die):
                yield {"event": "token", "id": i,
                       "data": {"token": self._tok(i)}}
            raise self._err("connection reset mid-stream", sent=True)
        assert body.get("resume_tokens") == \
            [self._tok(i) for i in range(self.jlen)], body
        for i in range(self.n):
            yield {"event": "token", "id": i,
                   "data": {"token": self._tok(i)}}
        yield {"event": "done", "id": self.n,
               "data": {"request_id": body["request_id"],
                        "finish_reason": "length"}}


def test_router_stream_kill_gapless_resume():
    """The 2-replica kill-mid-stream pin: the client's concatenated
    stream has event ids exactly 0..n-1 (no gap, no duplicate) and the
    journaled committed prefix is delivered BEFORE the retry replica
    even answers."""
    from fengshen_tpu.fleet import FleetConfig, FleetRouter

    t = _DyingStreamTransport()
    router = FleetRouter(
        FleetConfig(replicas=("a:1", "b:2"), recovery_probes=1,
                    seed=0),
        transport=t, clock=_ManualClock(), sleep=lambda s: None)
    router.poll_once()
    code, body, frames = router.route_generate_stream(
        {"input_text": "x"})
    assert code == 200 and body is None
    evs = list(iter_sse(b"".join(frames).decode().splitlines()))
    toks = [(e["id"], e["data"]["token"]) for e in evs
            if e["event"] == "token"]
    assert toks == [(i, 100 + i) for i in range(8)]
    assert evs[-1]["event"] == "done"
    # a:1 saw the fresh body, b:2 the journal-resumed one
    assert [n for n, _b in t.bodies] == ["a:1", "b:2"]
    assert "resume_tokens" not in t.bodies[0][1]


def test_router_stream_follows_evacuation():
    from fengshen_tpu.fleet import FleetConfig, FleetRouter

    class EvacTransport(_DyingStreamTransport):
        def stream(self, base_url, method, path, body, timeout_s):
            name = base_url.split("://", 1)[1]
            self.bodies.append((name, dict(body)))
            if name == "a:1":
                for i in range(2):
                    yield {"event": "token", "id": i,
                           "data": {"token": self._tok(i)}}
                yield {"event": "evacuated", "id": 2,
                       "data": {"request_id": body["request_id"],
                                "target": "http://b:2"}}
                return
            # the adopter sees a RECONNECT body, not a resubmit
            assert body.get("last_event_id") == 1, body
            assert "input_text" not in body
            for i in range(2, 6):
                yield {"event": "token", "id": i,
                       "data": {"token": self._tok(i)}}
            yield {"event": "done", "id": 6,
                   "data": {"request_id": body["request_id"],
                            "finish_reason": "eos"}}

    t = EvacTransport()
    router = FleetRouter(
        FleetConfig(replicas=("a:1", "b:2"), recovery_probes=1,
                    seed=0),
        transport=t, clock=_ManualClock(), sleep=lambda s: None)
    router.poll_once()
    _code, _body, frames = router.route_generate_stream(
        {"input_text": "x"})
    evs = list(iter_sse(b"".join(frames).decode().splitlines()))
    toks = [(e["id"], e["data"]["token"]) for e in evs
            if e["event"] == "token"]
    assert toks == [(i, 100 + i) for i in range(6)]
    assert evs[-1]["event"] == "done"


def test_router_stream_draining_refusal():
    from fengshen_tpu.fleet import FleetConfig, FleetRouter
    router = FleetRouter(
        FleetConfig(replicas=("a:1",), recovery_probes=1),
        transport=_DyingStreamTransport(), clock=_ManualClock(),
        sleep=lambda s: None)
    router.drain()
    code, body, frames = router.route_generate_stream(
        {"input_text": "x"})
    assert code == 503 and frames is None
    assert body["reason"] == "draining"


# ---- bench harness (the fast no-jax slice) ------------------------------

def test_stream_bench_kill_rung_real_http():
    """The serve-bench-stream kill rung over REAL stdlib SSE servers:
    abrupt replica death mid-stream, zero client-visible gaps."""
    from fengshen_tpu.streaming.bench import _kill_rung
    out = _kill_rung(new_tokens=12, kill_after=4)
    assert out["gapless"] is True
    assert out["token_identical"] is True
    assert out["terminal"] == "done"
    assert out["delivered"] == 12


def test_make_target_wired():
    mk = open(os.path.join(REPO, "Makefile")).read()
    assert "serve-bench-stream:" in mk
    assert "fengshen_tpu.streaming.bench" in mk


def test_benchdiff_identity_grows_stream_keys():
    from fengshen_tpu.observability.benchdiff import _identity
    row = {"metric": "m", "value": 1.0}
    assert _identity(row) == "none"       # old rows unchanged
    srow = dict(row, stream=True, spec_mode="self_draft")
    ident = _identity(srow)
    assert "stream=True" in ident and "spec_mode=self_draft" in ident
    assert _identity(dict(srow, spec_mode="prompt_lookup")) != ident
