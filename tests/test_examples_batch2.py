"""End-to-end smoke tests for the round-2 example workloads (tiny data,
8-device CPU mesh) — each runs the example's real main() CLI surface,
mirroring tests/test_examples.py (SURVEY.md §4)."""

import json
import os



import numpy as np
import pytest

pytestmark = pytest.mark.slow  # full-fit/e2e lane: run with -m slow or no -m filter



def _bert_tokenizer_dir(tmp_path):
    from transformers import BertTokenizer
    chars = list("今天天气很好我们去公园吧然后回家机器学习模型训练数据中文"
                 "测试句子北京是的首都问题答案知识摘要新闻标题内容一二三四五")
    vocab = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"] + \
        sorted(set(chars))
    vf = tmp_path / "vocab.txt"
    vf.write_text("\n".join(vocab))
    tok = BertTokenizer(str(vf))
    model_dir = tmp_path / "model"
    model_dir.mkdir(exist_ok=True)
    tok.save_pretrained(str(model_dir))
    return tok, model_dir


def _write_jsonl(path, rows):
    with open(path, "w") as f:
        for r in rows:
            f.write(json.dumps(r, ensure_ascii=False) + "\n")


def _common_args(tmp_path, model_dir, train, extra=()):
    return [
        "--model_path", str(model_dir), "--train_file", str(train),
        "--train_batchsize", "2", "--max_steps", "2",
        "--log_every_n_steps", "1", "--warmup_steps", "1",
        "--default_root_dir", str(tmp_path / "runs"),
        "--save_ckpt_path", str(tmp_path / "ckpt"),
        "--load_ckpt_path", str(tmp_path / "ckpt"),
        "--seed", "1", *extra]


def _assert_losses(tmp_path, n=2):
    lines = [json.loads(l) for l in open(tmp_path / "runs" / "metrics.jsonl")]
    losses = [l["loss"] for l in lines if "loss" in l]
    assert len(losses) == n and all(np.isfinite(losses)), losses


def test_pretrain_t5_e2e(tmp_path, mesh8):
    from fengshen_tpu.examples.pretrain_t5 import pretrain_t5
    from fengshen_tpu.models.t5 import T5Config
    tok, model_dir = _bert_tokenizer_dir(tmp_path)
    T5Config.small_test_config(vocab_size=len(tok) + 8).save_pretrained(
        str(model_dir))
    train = tmp_path / "train.json"
    _write_jsonl(train, [{"text": "今天天气很好我们去公园吧然后回家"}] * 8)
    pretrain_t5.main(_common_args(
        tmp_path, model_dir, train, ["--max_seq_length", "32"]))
    _assert_losses(tmp_path)

    # --do_eval_only: restore the just-saved checkpoint and run one
    # validation sweep, no training (reference:
    # pretrain_mt5_small_predict.sh)
    val = tmp_path / "val.json"
    _write_jsonl(val, [{"text": "机器学习模型训练数据"}] * 4)
    pretrain_t5.main(_common_args(
        tmp_path, model_dir, train,
        ["--max_seq_length", "32", "--do_eval_only",
         "--val_file", str(val), "--val_batchsize", "2"]))
    lines = [json.loads(l)
             for l in open(tmp_path / "runs" / "metrics.jsonl")]
    assert any("val_loss" in l for l in lines)
    assert any(l.get("event") == "validate_start" for l in lines)
    # no NEW training steps were taken
    losses = [l["loss"] for l in lines if "loss" in l]
    assert len(losses) == 2


def test_pretrain_t5_trim_vocab():
    import jax
    from fengshen_tpu.examples.pretrain_t5.pretrain_t5 import trim_vocab
    from fengshen_tpu.models.t5 import T5Config, T5ForConditionalGeneration
    import jax.numpy as jnp
    cfg = T5Config.small_test_config(vocab_size=64, tie_word_embeddings=False)
    model = T5ForConditionalGeneration(cfg)
    ids = jnp.zeros((1, 4), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids, ids)["params"]
    keep = list(range(0, 64, 2))
    trimmed = trim_vocab(params, keep)
    inner = trimmed["model"] if "model" in trimmed else trimmed
    assert inner["shared"]["embedding"].shape[0] == 32
    if "lm_head" in trimmed:
        assert trimmed["lm_head"]["kernel"].shape[-1] == 32


def test_pretrain_bert_e2e(tmp_path, mesh8):
    from fengshen_tpu.examples.pretrain_bert import pretrain_bert
    from fengshen_tpu.models.bert import BertConfig
    tok, model_dir = _bert_tokenizer_dir(tmp_path)
    BertConfig.small_test_config(vocab_size=len(tok)).save_pretrained(
        str(model_dir))
    train = tmp_path / "train.json"
    _write_jsonl(train, [{"text": "机器学习模型训练数据中文测试句子"}] * 8)
    pretrain_bert.main(_common_args(
        tmp_path, model_dir, train, ["--max_seq_length", "32"]))
    _assert_losses(tmp_path)


def test_pretrain_deberta_e2e(tmp_path, mesh8):
    from fengshen_tpu.examples.pretrain_erlangshen_deberta_v2 import (
        pretrain_deberta)
    from fengshen_tpu.models.deberta_v2 import DebertaV2Config
    tok, model_dir = _bert_tokenizer_dir(tmp_path)
    DebertaV2Config.small_test_config(vocab_size=len(tok)).save_pretrained(
        str(model_dir))
    train = tmp_path / "train.json"
    _write_jsonl(train, [{"text": "今天天气很好我们去公园吧然后回家"}] * 8)
    pretrain_deberta.main(_common_args(
        tmp_path, model_dir, train, ["--max_seq_length", "32"]))
    _assert_losses(tmp_path)


def test_pegasus_gsg_selection():
    from fengshen_tpu.examples.pegasus.pretrain_pegasus import (
        gap_sentence_ids, split_sentences)
    text = "今天天气很好。我们去公园吧！然后回家。机器学习模型训练。"
    sents = split_sentences(text)
    assert len(sents) == 4
    picked = gap_sentence_ids(sents, 0.25)
    assert len(picked) == 1 and 0 <= picked[0] < 4


def test_pretrain_pegasus_e2e(tmp_path, mesh8):
    from fengshen_tpu.examples.pegasus import pretrain_pegasus
    from fengshen_tpu.models.pegasus import PegasusConfig
    tok, model_dir = _bert_tokenizer_dir(tmp_path)
    PegasusConfig.small_test_config(vocab_size=len(tok)).save_pretrained(
        str(model_dir))
    train = tmp_path / "train.json"
    _write_jsonl(train, [{"text": "今天天气很好。我们去公园吧！然后回家。"
                                  "机器学习模型训练。"}] * 8)
    pretrain_pegasus.main(_common_args(
        tmp_path, model_dir, train,
        ["--max_seq_length", "32", "--max_target_length", "16"]))
    _assert_losses(tmp_path)


def test_qa_t5_e2e(tmp_path, mesh8):
    from fengshen_tpu.examples.qa_t5 import finetune_t5_cmrc
    from fengshen_tpu.models.t5 import T5Config
    tok, model_dir = _bert_tokenizer_dir(tmp_path)
    T5Config.small_test_config(vocab_size=len(tok)).save_pretrained(
        str(model_dir))
    train = tmp_path / "train.json"
    _write_jsonl(train, [{"question": "北京是什么",
                          "context": "北京是中国的首都",
                          "answer": ["首都"]}] * 8)
    finetune_t5_cmrc.main(_common_args(
        tmp_path, model_dir, train,
        ["--max_seq_length", "32", "--max_target_length", "16"]))
    _assert_losses(tmp_path)


def test_mt5_summary_e2e(tmp_path, mesh8):
    from fengshen_tpu.examples.mt5_summary import mt5_summary
    from fengshen_tpu.models.t5 import T5Config
    tok, model_dir = _bert_tokenizer_dir(tmp_path)
    T5Config.small_test_config(vocab_size=len(tok)).save_pretrained(
        str(model_dir))
    train = tmp_path / "train.json"
    _write_jsonl(train, [{"text": "今天天气很好我们去公园吧然后回家",
                          "summary": "天气很好"}] * 8)
    mt5_summary.main(_common_args(
        tmp_path, model_dir, train,
        ["--max_src_length", "32", "--max_tgt_length", "16"]))
    _assert_losses(tmp_path)


def test_bart_qg_collator_mask_styles(tmp_path):
    from fengshen_tpu.examples.finetune_bart_qg.finetune_bart import (
        BartQGCollator)
    tok, _ = _bert_tokenizer_dir(tmp_path)
    sample = {"context": "北京是中国的首都", "answer": ["北京"],
              "ans_span": [[0, 2]], "question": "中国的首都是哪里"}
    c_ans = BartQGCollator(tok, mask_ans_style="anstoken")
    assert c_ans.mask_context(sample) == "<ans>是中国的首都"
    c_un = BartQGCollator(tok, mask_ans_style="unmask")
    assert c_un.mask_context(sample) == "北京是中国的首都"
    c_norm = BartQGCollator(tok, mask_ans_style="normal")
    assert tok.mask_token in c_norm.mask_context(sample)


def test_bart_qg_e2e(tmp_path, mesh8):
    from fengshen_tpu.examples.finetune_bart_qg import finetune_bart
    from fengshen_tpu.models.bart import BartConfig
    tok, model_dir = _bert_tokenizer_dir(tmp_path)
    BartConfig.small_test_config(vocab_size=len(tok)).save_pretrained(
        str(model_dir))
    train = tmp_path / "train.json"
    _write_jsonl(train, [{"context": "北京是中国的首都",
                          "answer": ["北京"], "ans_span": [[0, 2]],
                          "question": "中国的首都是哪里"}] * 8)
    finetune_bart.main(_common_args(
        tmp_path, model_dir, train,
        ["--max_seq_length", "32", "--max_target_length", "16"]))
    _assert_losses(tmp_path)


@pytest.mark.parametrize("model_type", ["bert-linear", "bert-crf",
                                        "bert-span"])
def test_sequence_tagging_e2e(tmp_path, mesh8, model_type):
    from fengshen_tpu.examples.sequence_tagging import (
        finetune_sequence_tagging)
    from fengshen_tpu.models.megatron_bert import MegatronBertConfig
    tok, model_dir = _bert_tokenizer_dir(tmp_path)
    MegatronBertConfig.small_test_config(
        vocab_size=len(tok)).save_pretrained(str(model_dir))
    data_dir = tmp_path / "data"
    data_dir.mkdir()
    conll = "\n".join(["北 B-LOC", "京 I-LOC", "是 O", "首 O", "都 O", "",
                       "中 B-LOC", "国 I-LOC", "很 O", "大 O", ""])
    (data_dir / "train.char.bio").write_text(conll * 4)
    finetune_sequence_tagging.main(_common_args(
        tmp_path, model_dir, tmp_path / "unused.json",
        ["--max_seq_length", "32", "--model_type", model_type,
         "--data_dir", str(data_dir)]))
    _assert_losses(tmp_path)


def test_qa_t5_predict_only(tmp_path, mesh8):
    """run_predict.sh path: --do_eval_only decodes the test split into
    --prediction_res_path without training."""
    from fengshen_tpu.examples.qa_t5 import finetune_t5_cmrc
    from fengshen_tpu.models.t5 import T5Config
    tok, model_dir = _bert_tokenizer_dir(tmp_path)
    T5Config.small_test_config(vocab_size=len(tok)).save_pretrained(
        str(model_dir))
    test = tmp_path / "test.json"
    _write_jsonl(test, [{"question": "北京是什么",
                         "context": "北京是中国的首都",
                         "answer": ["首都"]}] * 4)
    res = tmp_path / "predictions.txt"
    finetune_t5_cmrc.main([
        "--model_path", str(model_dir),
        "--test_file", str(test),
        "--do_eval_only",
        "--prediction_res_path", str(res),
        "--test_batchsize", "2",
        "--max_seq_length", "32", "--max_target_length", "8",
        "--default_root_dir", str(tmp_path / "runs"),
        "--save_ckpt_path", str(tmp_path / "ckpt"),
        "--load_ckpt_path", str(tmp_path / "ckpt"),
        "--precision", "fp32"])
    lines = res.read_text().splitlines()
    assert len(lines) == 4
