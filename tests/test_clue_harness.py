"""Dry-run of the FewCLUE/ZeroCLUE quality harness (VERDICT r2 #5):
a randomly-initialized checkpoint WRITTEN IN THE REFERENCE'S OWN FORMAT
(HF MegatronBertForMaskedLM state dict + config.json + tokenizer files)
goes through load → convert → task eval → comparison table, end to end.
The day a published checkpoint is reachable, parity is one command.
"""

import json

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # full-fit/e2e lane: run with -m slow or no -m filter

torch = pytest.importorskip("torch")


def _make_reference_checkpoint(tmp_path):
    """Reference-format UniMC checkpoint dir with a tiny random model."""
    from transformers import BertTokenizer
    from transformers import MegatronBertConfig as HFCfg
    from transformers import MegatronBertForMaskedLM as HFMLM

    chars = list("今天天气很好我们去公园吧然后回家机器学习模型训练数据中文"
                 "测试句子北京是的首都问题答案好评差评体育军事财经科技否")
    vocab = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"] + \
        sorted(set(chars))
    ckpt = tmp_path / "unimc_ckpt"
    ckpt.mkdir()
    (ckpt / "vocab.txt").write_text("\n".join(vocab))
    BertTokenizer(str(ckpt / "vocab.txt")).save_pretrained(str(ckpt))

    hf_cfg = HFCfg(vocab_size=len(vocab), hidden_size=32,
                   num_hidden_layers=2, num_attention_heads=4,
                   intermediate_size=64, max_position_embeddings=64,
                   type_vocab_size=2)
    torch.manual_seed(0)
    model = HFMLM(hf_cfg)
    # the reference UniMCModel holds the MLM tower under attr `bert`
    sd = {f"bert.{k}": v for k, v in model.state_dict().items()}
    torch.save(sd, ckpt / "pytorch_model.bin")
    (ckpt / "config.json").write_text(json.dumps({
        "vocab_size": len(vocab), "hidden_size": 32,
        "num_hidden_layers": 2, "num_attention_heads": 4,
        "intermediate_size": 64, "max_position_embeddings": 64,
        "type_vocab_size": 2, "dtype": "float32",
        "model_type": "megatron-bert"}))
    return ckpt


def _make_task_files(tmp_path):
    data = tmp_path / "clue_data"
    data.mkdir()
    rows = [
        {"texta": "今天天气很好", "textb": "", "question": "",
         "choice": ["这是一条好评", "这是一条差评"], "label": 0},
        {"texta": "机器学习模型", "textb": "", "question": "",
         "choice": ["这是一条好评", "这是一条差评"], "label": 1},
        {"texta": "北京是中国的首都", "textb": "",
         "question": "下面句子的类别是",
         "choice": ["体育", "军事", "财经"], "label": 2},
    ]
    for task in ("eprstmt", "tnews"):
        with open(data / f"{task}.jsonl", "w") as f:
            for r in rows:
                f.write(json.dumps(r, ensure_ascii=False) + "\n")
    return data


def test_clue_harness_end_to_end(tmp_path, capsys):
    from fengshen_tpu.metrics.clue_harness import run

    ckpt = _make_reference_checkpoint(tmp_path)
    data = _make_task_files(tmp_path)
    results = run(str(ckpt), str(data), mode="zero_shot",
                  tasks=["eprstmt", "tnews"], batch_size=2,
                  max_length=64)
    assert set(results) == {"eprstmt", "tnews", "avg"}
    for v in results.values():
        assert 0.0 <= v <= 100.0
    out = capsys.readouterr().out
    assert "published" in out and "eprstmt" in out
    # the table compares against the published zero-shot row
    assert "88.79" in out


def test_unimc_reference_scoring_matches_torch(tmp_path):
    """The harness encoding (block-diagonal mask + position restarts +
    yes-token scoring) must reproduce the reference UniMCModel.forward
    (modeling_unimc.py:297-345) on the converted weights."""
    from fengshen_tpu.metrics.clue_harness import (collate_unimc,
                                                   encode_unimc,
                                                   load_unimc_checkpoint)

    ckpt = _make_reference_checkpoint(tmp_path)
    model, params, tokenizer = load_unimc_checkpoint(str(ckpt))

    item = {"texta": "今天天气很好", "textb": "", "question": "",
            "choice": ["好评", "差评"], "label": 0}
    enc = encode_unimc(item, tokenizer, max_length=64)
    batch = collate_unimc([enc])

    import jax.numpy as jnp
    scores = model.apply(
        {"params": params}, jnp.asarray(batch["input_ids"]),
        attention_mask=jnp.asarray(batch["attention_mask"]),
        token_type_ids=jnp.asarray(batch["token_type_ids"]),
        option_positions=jnp.asarray(batch["option_positions"]),
        position_ids=jnp.asarray(batch["position_ids"]))

    # torch oracle: reference forward = MLM logits at option mask
    # positions, yes-token column
    from transformers import MegatronBertForMaskedLM as HFMLM
    from transformers import MegatronBertConfig as HFCfg
    sd = torch.load(ckpt / "pytorch_model.bin", weights_only=False)
    hf_cfg = HFCfg(vocab_size=model.config.vocab_size, hidden_size=32,
                   num_hidden_layers=2, num_attention_heads=4,
                   intermediate_size=64, max_position_embeddings=64,
                   type_vocab_size=2)
    tm = HFMLM(hf_cfg).eval()
    tm.load_state_dict({k[len("bert."):]: v for k, v in sd.items()})
    yes_id = tokenizer.convert_tokens_to_ids("是")
    with torch.no_grad():
        # HF MegatronBert expands a [B, S, S] mask to additive form
        logits = tm(
            torch.tensor(batch["input_ids"], dtype=torch.long),
            attention_mask=torch.tensor(batch["attention_mask"],
                                        dtype=torch.float),
            token_type_ids=torch.tensor(batch["token_type_ids"],
                                        dtype=torch.long),
            position_ids=torch.tensor(batch["position_ids"],
                                      dtype=torch.long)).logits
    ref = logits[0, batch["option_positions"][0], yes_id].numpy()
    np.testing.assert_allclose(np.asarray(scores)[0], ref, atol=3e-4)
