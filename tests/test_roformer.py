"""RoFormer golden-value parity vs HF torch."""

import jax.numpy as jnp
import numpy as np
import pytest

from fengshen_tpu.models.roformer import RoFormerConfig, RoFormerModel
from fengshen_tpu.models.roformer.convert import torch_to_params


def test_roformer_forward_parity():
    torch = pytest.importorskip("torch")
    import transformers
    hf_cfg = transformers.RoFormerConfig(
        vocab_size=128, embedding_size=32, hidden_size=32,
        num_hidden_layers=2, num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, rotary_value=False,
        attn_implementation="eager")
    torch.manual_seed(0)
    tm = transformers.RoFormerModel(hf_cfg).eval()
    cfg = RoFormerConfig(vocab_size=128, hidden_size=32,
                         num_hidden_layers=2, num_attention_heads=4,
                         intermediate_size=64, max_position_embeddings=64,
                         dtype="float32")
    sd = {f"roformer.{k}": v for k, v in tm.state_dict().items()}
    params = torch_to_params(sd, cfg)["roformer"]  # top-level apply: unnest
    model = RoFormerModel(cfg, add_pooling_layer=False)
    ids = np.array([[3, 17, 9, 42, 7, 99, 1, 5]], dtype=np.int32)
    mask = np.array([[1, 1, 1, 1, 1, 1, 1, 0]], dtype=np.int32)
    hidden, _ = model.apply({"params": params},
                            jnp.asarray(ids),
                            attention_mask=jnp.asarray(mask))
    with torch.no_grad():
        ref = tm(torch.tensor(ids, dtype=torch.long),
                 attention_mask=torch.tensor(mask, dtype=torch.long)
                 ).last_hidden_state.numpy()
    np.testing.assert_allclose(np.asarray(hidden), ref, atol=2e-3)
