"""Hubert audio pretraining tests."""

import pytest
import jax
import jax.numpy as jnp
import numpy as np

pytestmark = pytest.mark.slow  # full-fit/e2e lane: run with -m slow or no -m filter


def test_mask_indices():
    from fengshen_tpu.models.hubert import compute_mask_indices
    rng = np.random.RandomState(0)
    mask = compute_mask_indices((2, 50), mask_prob=0.5, mask_length=5,
                                rng=rng)
    assert mask.shape == (2, 50)
    frac = mask.mean()
    assert 0.1 < frac < 0.9


def test_hubert_forward_and_loss():
    from fengshen_tpu.models.hubert import (HubertConfig, HubertModel,
                                            hubert_pretrain_loss,
                                            compute_mask_indices)
    cfg = HubertConfig.small_test_config()
    model = HubertModel(cfg)
    wav = jnp.asarray(np.random.RandomState(0).randn(2, 400), jnp.float32)
    params = model.init(jax.random.PRNGKey(0), wav)["params"]
    logits, hidden = model.apply({"params": params}, wav)
    n_frames = logits.shape[1]
    assert n_frames < 400 and logits.shape[-1] == 16

    rng = np.random.RandomState(1)
    mask = jnp.asarray(compute_mask_indices((2, n_frames), 0.5, 2, rng))
    targets = jnp.asarray(rng.randint(0, 16, (2, n_frames)))
    logits_m, _ = model.apply({"params": params}, wav,
                              mask_time_indices=mask)
    # masked frames produce different logits than unmasked run
    assert float(jnp.abs(logits_m - logits).max()) > 1e-6
    loss, n = hubert_pretrain_loss(logits_m, targets, mask)
    assert np.isfinite(float(loss)) and int(n) == int(mask.sum())
    loss2, _ = hubert_pretrain_loss(logits_m, targets, mask,
                                    unmasked_weight=0.5)
    assert float(loss2) != float(loss)
