"""Hubert audio pretraining tests."""

import pytest
import jax
import jax.numpy as jnp
import numpy as np

pytestmark = pytest.mark.slow  # full-fit/e2e lane: run with -m slow or no -m filter


def test_mask_indices():
    from fengshen_tpu.models.hubert import compute_mask_indices
    rng = np.random.RandomState(0)
    mask = compute_mask_indices((2, 50), mask_prob=0.5, mask_length=5,
                                rng=rng)
    assert mask.shape == (2, 50)
    frac = mask.mean()
    assert 0.1 < frac < 0.9


def test_hubert_forward_and_loss():
    from fengshen_tpu.models.hubert import (HubertConfig, HubertModel,
                                            hubert_pretrain_loss,
                                            compute_mask_indices)
    cfg = HubertConfig.small_test_config()
    model = HubertModel(cfg)
    wav = jnp.asarray(np.random.RandomState(0).randn(2, 400), jnp.float32)
    params = model.init(jax.random.PRNGKey(0), wav)["params"]
    logits, hidden = model.apply({"params": params}, wav)
    n_frames = logits.shape[1]
    assert n_frames < 400 and logits.shape[-1] == 16

    rng = np.random.RandomState(1)
    mask = jnp.asarray(compute_mask_indices((2, n_frames), 0.5, 2, rng))
    targets = jnp.asarray(rng.randint(0, 16, (2, n_frames)))
    logits_m, _ = model.apply({"params": params}, wav,
                              mask_time_indices=mask)
    # masked frames produce different logits than unmasked run
    assert float(jnp.abs(logits_m - logits).max()) > 1e-6
    loss, n = hubert_pretrain_loss(logits_m, targets, mask)
    assert np.isfinite(float(loss)) and int(n) == int(mask.sum())
    loss2, _ = hubert_pretrain_loss(logits_m, targets, mask,
                                    unmasked_weight=0.5)
    assert float(loss2) != float(loss)


def _hf_parity_case(feat_extract_norm):
    torch = pytest.importorskip("torch")
    import transformers

    from fengshen_tpu.models.hubert import HubertConfig, HubertModel
    from fengshen_tpu.models.hubert.convert import torch_to_params

    hf_cfg = transformers.HubertConfig(
        hidden_size=32, num_hidden_layers=2, num_attention_heads=4,
        intermediate_size=64, conv_dim=(16, 16), conv_kernel=(10, 3),
        conv_stride=(5, 2), num_feat_extract_layers=2,
        num_conv_pos_embeddings=7, num_conv_pos_embedding_groups=4,
        feat_extract_norm=feat_extract_norm, do_stable_layer_norm=False,
        conv_bias=(feat_extract_norm == "layer"),
        feat_proj_dropout=0.0, hidden_dropout=0.0, attention_dropout=0.0,
        activation_dropout=0.0, layerdrop=0.0, feat_proj_layer_norm=True,
        attn_implementation="eager")
    torch.manual_seed(0)
    tm = transformers.HubertModel(hf_cfg).eval()

    cfg = HubertConfig(conv_layers=((16, 10, 5), (16, 3, 2)),
                       hidden_size=32, num_hidden_layers=2,
                       num_attention_heads=4, intermediate_size=64,
                       pos_conv_kernel=7, pos_conv_groups=4,
                       feat_extract_norm=feat_extract_norm,
                       hidden_dropout_prob=0.0,
                       attention_probs_dropout_prob=0.0)
    params = torch_to_params(tm.state_dict(), cfg)
    # no fairseq final_proj in the HF fine-tune format: graft a zero head
    model = HubertModel(cfg)
    wav = np.random.RandomState(1).randn(2, 400).astype(np.float32)
    init = model.init(jax.random.PRNGKey(0),
                      jnp.asarray(wav))["params"]
    params["cluster_head"] = init["cluster_head"]
    if "mask_embedding" not in params:
        params["mask_embedding"] = init["mask_embedding"]

    _, hidden = model.apply({"params": params}, jnp.asarray(wav))
    with torch.no_grad():
        ref = tm(torch.tensor(wav)).last_hidden_state.numpy()
    np.testing.assert_allclose(np.asarray(hidden), ref, atol=3e-4)


def test_hubert_hf_parity_group_norm():
    """Released-architecture parity (hubert-base layout): channel-wise
    GroupNorm conv encoder, pre-projection LayerNorm, SamePad-trimmed
    weight-normed pos conv, encoder LayerNorm — our flax tower must
    reproduce transformers.HubertModel exactly (VERDICT r4 weak #6)."""
    _hf_parity_case("group")


def test_hubert_hf_parity_layer_norm_convs():
    """conv-encoder "layer" mode (biased convs + per-layer LayerNorm,
    the hubert-large extractor) against the HF oracle."""
    _hf_parity_case("layer")


def test_hubert_hf_parity_stable_layer_norm():
    """hubert-large's full encoder: "layer" conv norms AND the pre-LN
    stable transformer (encoder LayerNorm after the stack)."""
    torch = pytest.importorskip("torch")
    import transformers

    from fengshen_tpu.models.hubert import HubertConfig, HubertModel
    from fengshen_tpu.models.hubert.convert import torch_to_params

    hf_cfg = transformers.HubertConfig(
        hidden_size=32, num_hidden_layers=2, num_attention_heads=4,
        intermediate_size=64, conv_dim=(16, 16), conv_kernel=(10, 3),
        conv_stride=(5, 2), num_feat_extract_layers=2,
        num_conv_pos_embeddings=7, num_conv_pos_embedding_groups=4,
        feat_extract_norm="layer", do_stable_layer_norm=True,
        conv_bias=True, feat_proj_dropout=0.0, hidden_dropout=0.0,
        attention_dropout=0.0, activation_dropout=0.0, layerdrop=0.0,
        feat_proj_layer_norm=True, attn_implementation="eager")
    torch.manual_seed(0)
    tm = transformers.HubertModel(hf_cfg).eval()

    cfg = HubertConfig(conv_layers=((16, 10, 5), (16, 3, 2)),
                       hidden_size=32, num_hidden_layers=2,
                       num_attention_heads=4, intermediate_size=64,
                       pos_conv_kernel=7, pos_conv_groups=4,
                       feat_extract_norm="layer",
                       do_stable_layer_norm=True,
                       hidden_dropout_prob=0.0,
                       attention_probs_dropout_prob=0.0)
    params = torch_to_params(tm.state_dict(), cfg)
    model = HubertModel(cfg)
    wav = np.random.RandomState(5).randn(2, 400).astype(np.float32)
    init = model.init(jax.random.PRNGKey(0), jnp.asarray(wav))["params"]
    params["cluster_head"] = init["cluster_head"]
    params.setdefault("mask_embedding", init["mask_embedding"])

    _, hidden = model.apply({"params": params}, jnp.asarray(wav))
    with torch.no_grad():
        ref = tm(torch.tensor(wav)).last_hidden_state.numpy()
    np.testing.assert_allclose(np.asarray(hidden), ref, atol=3e-4)
