"""Host-resident parameter streaming (trainer/param_streaming.py — the
ZeRO-3/offload-param analog, VERDICT r4 missing #4).

The streamed step must be EXACTLY the monolithic jitted step, just
scheduled differently: same loss, same post-update params as
optax.chain(clip_by_global_norm, adamw) over the whole tree at once.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from fengshen_tpu.trainer.param_streaming import (
    StreamedAdamW, llama_stream_spec, make_streamed,
    megatron_classifier_stream_spec)

HP = dict(learning_rate=3e-3, weight_decay=0.01, clip_norm=1.0)


def _ref_update(loss_fn, params, batch, steps=2):
    tx = optax.chain(optax.clip_by_global_norm(HP["clip_norm"]),
                     optax.adamw(HP["learning_rate"],
                                 weight_decay=HP["weight_decay"]))
    opt = tx.init(params)
    losses = []
    step = jax.jit(lambda p, o, b: _step(p, o, b))

    def _step(p, o, b):
        loss, grads = jax.value_and_grad(loss_fn)(p, b)
        upd, o = tx.update(grads, o, p)
        return optax.apply_updates(p, upd), o, loss

    for _ in range(steps):
        params, opt, loss = step(params, opt, batch)
        losses.append(float(loss))
    return params, losses


def _assert_tree_close(a, b, atol=2e-5):
    fa = jax.tree_util.tree_flatten_with_path(a)[0]
    fb = dict(jax.tree_util.tree_flatten_with_path(b)[0])
    assert len(fa) == len(fb)
    for path, leaf in fa:
        np.testing.assert_allclose(
            np.asarray(leaf, np.float32),
            np.asarray(fb[path], np.float32), atol=atol,
            err_msg=jax.tree_util.keystr(path))


@pytest.mark.parametrize("scan", [True, False])
def test_llama_streamed_step_matches_monolithic(scan):
    from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from fengshen_tpu.parallel.cross_entropy import stable_cross_entropy

    cfg = LlamaConfig(vocab_size=97, hidden_size=32, intermediate_size=64,
                      num_hidden_layers=3, num_attention_heads=4,
                      max_position_embeddings=32, dtype="float32",
                      param_dtype="float32", scan_layers=scan)
    model = LlamaForCausalLM(cfg)
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(1, 96, (2, 16)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids[:, :8])["params"]
    batch = {"input_ids": ids}

    def loss_fn(p, b):
        logits = model.apply({"params": p}, b["input_ids"])
        return stable_cross_entropy(logits[:, :-1],
                                    b["input_ids"][:, 1:])[0]

    ref_params, ref_losses = _ref_update(loss_fn, params, batch)

    eng = make_streamed(llama_stream_spec(cfg, params), **HP)
    losses = [eng.step(batch)[0] for _ in range(2)]
    np.testing.assert_allclose(losses, ref_losses, atol=1e-5)
    # 5e-5, not the default 2e-5: the scan-layers variant reassociates
    # the per-layer grad reductions and this jax/CPU build lands one
    # v_proj element at 2.16e-5 off after two adamw steps (NOTES.md
    # tier-1 triage) — same math, looser float path
    _assert_tree_close(eng.params(), ref_params, atol=5e-5)


def test_megatron_classifier_streamed_step_matches_monolithic():
    from fengshen_tpu.examples.classification.finetune_classification \
        import TaskModel
    from fengshen_tpu.models.megatron_bert import MegatronBertConfig
    from fengshen_tpu.parallel.cross_entropy import stable_cross_entropy

    cfg = MegatronBertConfig(
        vocab_size=97, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, dtype="float32",
        param_dtype="float32", hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0)
    model = TaskModel(cfg, "huggingface-megatron_bert", num_labels=3)
    rng = np.random.RandomState(1)
    ids = jnp.asarray(rng.randint(1, 96, (4, 12)), jnp.int32)
    mask = jnp.ones_like(ids)
    labels = jnp.asarray(rng.randint(0, 3, (4,)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids)["params"]
    batch = {"input_ids": ids, "attention_mask": mask, "labels": labels}

    def loss_fn(p, b):
        logits = model.apply({"params": p}, b["input_ids"],
                             attention_mask=b["attention_mask"])
        return stable_cross_entropy(logits[:, None, :],
                                    b["labels"][:, None])[0]

    ref_params, ref_losses = _ref_update(loss_fn, params, batch)

    eng = make_streamed(
        megatron_classifier_stream_spec(cfg, params, num_labels=3), **HP)
    losses = [eng.step(batch)[0] for _ in range(2)]
    np.testing.assert_allclose(losses, ref_losses, atol=1e-5)
    _assert_tree_close(eng.params(), ref_params)
    # metrics come through
    _, metrics = eng.step(batch)
    assert "acc" in metrics and "grad_norm" in metrics


def test_streamed_reduced_moments_close_to_fp32():
    """moments_dtype='bfloat16' stores the adam moments reduced (the
    host-memory term that bounds streamable model size) with fp32
    update math: a few steps must track the fp32-moment run closely,
    and the host arrays must actually BE bf16."""
    from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from fengshen_tpu.parallel.cross_entropy import stable_cross_entropy

    cfg = LlamaConfig(vocab_size=97, hidden_size=32, intermediate_size=64,
                      num_hidden_layers=2, num_attention_heads=4,
                      max_position_embeddings=32, dtype="float32",
                      param_dtype="float32")
    model = LlamaForCausalLM(cfg)
    rng = np.random.RandomState(3)
    ids = jnp.asarray(rng.randint(1, 96, (2, 16)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids[:, :8])["params"]
    batch = {"input_ids": ids}

    def loss_fn(p, b):
        logits = model.apply({"params": p}, b["input_ids"])
        return stable_cross_entropy(logits[:, :-1],
                                    b["input_ids"][:, 1:])[0]

    ref_params, ref_losses = _ref_update(loss_fn, params, batch, steps=3)

    eng = make_streamed(llama_stream_spec(cfg, params), **HP,
                        moments_dtype="bfloat16")
    for part_m, part_v in zip(eng.m, eng.v):
        for leaf in (jax.tree_util.tree_leaves(part_m) +
                     jax.tree_util.tree_leaves(part_v)):
            assert leaf.dtype == jnp.bfloat16
    losses = [eng.step(batch)[0] for _ in range(3)]
    # bf16 moment storage perturbs the trajectory slightly; it must
    # stay close to the fp32 run, not bit-equal
    np.testing.assert_allclose(losses, ref_losses, atol=5e-3)
    _assert_tree_close(eng.params(), ref_params, atol=5e-3)
    # still bf16 after updates round-tripped (both moments: dropping
    # the v cast-back would silently restore the fp32 memory blow-up)
    for part_m, part_v in zip(eng.m, eng.v):
        for leaf in (jax.tree_util.tree_leaves(part_m) +
                     jax.tree_util.tree_leaves(part_v)):
            assert leaf.dtype == jnp.bfloat16


def test_streamed_clip_engages():
    """With a tiny clip threshold the streamed update must scale exactly
    like optax.clip_by_global_norm."""
    from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from fengshen_tpu.parallel.cross_entropy import stable_cross_entropy

    cfg = LlamaConfig(vocab_size=61, hidden_size=16, intermediate_size=32,
                      num_hidden_layers=2, num_attention_heads=2,
                      max_position_embeddings=16, dtype="float32",
                      param_dtype="float32", scan_layers=True)
    model = LlamaForCausalLM(cfg)
    ids = jnp.asarray(
        np.random.RandomState(2).randint(1, 60, (2, 8)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids)["params"]
    batch = {"input_ids": ids}

    def loss_fn(p, b):
        logits = model.apply({"params": p}, b["input_ids"])
        return stable_cross_entropy(logits[:, :-1],
                                    b["input_ids"][:, 1:])[0]

    hp = dict(HP, clip_norm=1e-3)  # definitely engages
    tx = optax.chain(optax.clip_by_global_norm(1e-3),
                     optax.adamw(hp["learning_rate"],
                                 weight_decay=hp["weight_decay"]))
    opt = tx.init(params)
    loss, grads = jax.value_and_grad(loss_fn)(params, batch)
    upd, opt = tx.update(grads, opt, params)
    ref_params = optax.apply_updates(params, upd)

    eng = make_streamed(llama_stream_spec(cfg, params), **hp)
    eng.step(batch)
    _assert_tree_close(eng.params(), ref_params)


@pytest.mark.slow
def test_offload_params_e2e(tmp_path, mesh8):
    """finetune_classification --offload_params: the streamed AFQMC
    recipe end-to-end (train → predict → save_test)."""
    import json

    from tests.test_classification_port import (_write_model_dir,
                                                _write_task_dir)
    from fengshen_tpu.examples.classification import (
        finetune_classification as fc)

    data_dir = _write_task_dir(tmp_path)
    model_dir = _write_model_dir(tmp_path, model_type="megatron-bert")
    out = tmp_path / "pred.json"
    fc.main([
        "--data_dir", str(data_dir), "--train_data", "train.json",
        "--valid_data", "dev.json", "--test_data", "test.json",
        "--pretrained_model_path", str(model_dir),
        "--model_type", "huggingface-megatron_bert",
        "--texta_name", "sentence1", "--textb_name", "sentence2",
        "--max_length", "32", "--train_batchsize", "4",
        "--valid_batchsize", "4", "--max_epochs", "1",
        "--learning_rate", "1e-4", "--offload_params",
        "--output_save_path", str(out),
        "--default_root_dir", str(tmp_path / "runs"),
        "--precision", "fp32"])
    lines = [json.loads(x) for x in open(str(out) + ".0")]
    assert len(lines) == 6
    assert sorted(l["id"] for l in lines) == list(range(6))


@pytest.mark.slow
def test_ziya_offload_params_e2e(tmp_path, mesh8, capsys):
    """finetune_ziya_llama --offload_params: the flagship SFT recipe
    through the streaming engine (the 13B-finetune mechanism at tiny
    shape)."""
    import json
    import unittest.mock as mock

    from fengshen_tpu.examples.ziya_llama import finetune_ziya_llama
    from fengshen_tpu.models.llama import LlamaConfig

    model_dir = tmp_path / "model"
    model_dir.mkdir()

    class CharTok:
        pad_token_id = 0
        eos_token_id = 2

        def encode(self, text, add_special_tokens=True):
            ids = [min(3 + (ord(c) % 90), 95) for c in text]
            return ([1] + ids) if add_special_tokens else ids

        @classmethod
        def from_pretrained(cls, path):
            return cls()

    cfg = LlamaConfig(vocab_size=128, hidden_size=32,
                      intermediate_size=64, num_hidden_layers=2,
                      num_attention_heads=4, max_position_embeddings=64,
                      dtype="float32", param_dtype="float32")
    cfg.save_pretrained(str(model_dir))
    train = tmp_path / "sft.json"
    with open(train, "w") as f:
        for i in range(8):
            f.write(json.dumps({"query": "你好" * (1 + i % 3),
                                "answer": "hello"},
                               ensure_ascii=False) + "\n")

    with mock.patch("transformers.AutoTokenizer.from_pretrained",
                    CharTok.from_pretrained):
        finetune_ziya_llama.main([
            "--model_path", str(model_dir), "--train_file", str(train),
            "--train_batchsize", "4", "--max_steps", "2",
            "--max_seq_length", "32", "--log_every_n_steps", "1",
            "--warmup_steps", "1", "--offload_params",
            "--offload_moments_dtype", "bfloat16",
            "--default_root_dir", str(tmp_path / "runs"),
            "--save_ckpt_path", str(tmp_path / "ckpt"),
            "--load_ckpt_path", str(tmp_path / "ckpt"),
            "--seed", "1"])
    out = capsys.readouterr().out
    assert "[streamed] step=2" in out
