"""Preemption-tolerant serving (ISSUE 16, docs/fault_tolerance.md
"Preemption runbook"): live lane evacuation on drain +
resume-from-token-k failover.

Four tiers:

- ENGINE tests over the drain/evacuation path: THE acceptance pin —
  a lane primed mid-decode, evacuated through
  `DisaggCoordinator.evacuate_all` (probe → rank → export → push →
  detach-as-evacuated) and finished by the adopter is token-identical
  to the single-engine baseline across slot AND paged layouts and the
  int8 wire, with compile counts pinned (evacuation adds ZERO jitted
  programs); plus the `begin_drain` queue-flush contract (queued
  requests reject as orderly "draining" NOW, without touching the
  pinned `rejected_draining` submit-refusal counter);
- RESUME tests over `submit(resume_tokens=...)`: prefilling
  prompt+committed-prefix and decoding only the remainder reproduces
  the undisturbed greedy output exactly, across layouts, again with
  pinned compile counts — and the journal ring (`partial()`) serves
  the snapshots that make it possible;
- HTTP tests over REAL stdlib replicas behind the REAL `FleetRouter`
  with a `FleetFaultPlan`: the `preempt` fault delivers a drain at an
  exact request index — every in-flight request answers 200
  token-identical through evacuation redirects (zero resumes, zero
  client errors) with `evacuated`/`adopted` on the two timelines; and
  the SIGKILL variant (adopter hard-killed right after adopting)
  recovers every request through the commit journal:
  `fstpu_resume_total{outcome="resumed"}` >= 1, zero journal misses,
  `resumed_from` on the rescuer's timeline, and ONE assembled trace
  stitching the drained and rescuing replicas.
"""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from fengshen_tpu.disagg.coordinator import DisaggCoordinator
from fengshen_tpu.fleet import (FleetConfig, FleetFaultPlan,
                                FleetRouter, UrllibTransport)
from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from fengshen_tpu.pipelines.text_generation import Pipeline
from fengshen_tpu.serving import (ContinuousBatchingEngine,
                                  EngineConfig)
from fengshen_tpu.serving.engine import Draining
from fengshen_tpu.utils.generate import generate

PAGED = dict(kv_layout="paged", kv_block_size=8, kv_num_blocks=17)


@pytest.fixture(scope="module")
def tiny():
    cfg = LlamaConfig(vocab_size=97, hidden_size=32,
                      intermediate_size=64, num_hidden_layers=2,
                      num_attention_heads=4,
                      max_position_embeddings=64, dtype="float32")
    model = LlamaForCausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 4), jnp.int32))["params"]
    return model, params


class _IntTok:
    eos_token_id = None
    pad_token_id = 0

    def encode(self, text):
        return [int(t) for t in text.split()]

    def decode(self, ids):
        return " ".join(str(int(t)) for t in ids)


def _ref(model, params, prompt, max_new):
    out = np.asarray(generate(model, params, jnp.asarray(prompt)[None],
                              max_new_tokens=max_new))
    return out[0, len(prompt):].tolist()


_PROMPT = np.random.RandomState(0).randint(3, 96, 6).astype(np.int32)
_MAX_NEW = 12


def _mk_engine(tiny, **kw):
    model, params = tiny
    kw = dict({"num_slots": 2, "buckets": (8,)}, **kw)
    buckets = kw.pop("buckets")
    return ContinuousBatchingEngine(
        model, params,
        EngineConfig(buckets=buckets, max_new_tokens=_MAX_NEW,
                     pad_token_id=0, **kw))


def _prime(engine, ticks=4):
    req = engine.submit(_PROMPT)
    engine.step()                       # admit + prefill + first token
    for _ in range(ticks):
        engine.step()
    assert req.state == "running"
    return req


def _pipe(tiny, max_new=_MAX_NEW):
    model, params = tiny
    return Pipeline(module=model, params=params, tokenizer=_IntTok(),
                    max_new_tokens=max_new, eos_token_id=None,
                    pad_token_id=0)


def _labelled(counter):
    return {k[0]: int(c.value) for k, c in counter.children()
            if c.value}


class _Loopback:
    """In-process peer wire for `evacuate_all`: /stats probes, KV
    pushes, and twin deletes delivered straight to the destination
    coordinator — no sockets, no jax programs."""

    def __init__(self):
        self.peers = {}                 # base url -> coordinator

    def request(self, base_url, method, path, body, timeout_s):
        coord = self.peers[base_url.rstrip("/")]
        if method == "GET" and path == "/stats":
            st = coord.engine.stats()
            return 200, {
                "slots_active": int(st.get("slots_active") or 0),
                "queue_depth": int(st.get("queue_depth") or 0),
                "num_slots": coord.engine.config.num_slots,
                "draining": False, "phase": "both"}
        if method == "PUT" and path.startswith("/kv/"):
            return coord.handle_put(path[len("/kv/"):], body)
        if method == "DELETE" and path.startswith("/kv/"):
            return 200, {"deleted": True}
        return 404, {"error": "not found"}


# ---- engine tier: live lane evacuation ----------------------------------

@pytest.mark.parametrize("name,src_kw,dst_kw", [
    ("fp32slot->fp32slot", {}, {}),
    ("fp32slot->fp32paged", {}, PAGED),
    ("int8paged->fp32slot", dict(kv_dtype="int8", **PAGED), {}),
    ("int8slot->int8paged", dict(kv_dtype="int8"),
     dict(kv_dtype="int8", **PAGED)),
])
def test_evacuation_token_identity(tiny, name, src_kw, dst_kw):
    """THE acceptance pin: a draining engine's live lane, evacuated
    through the coordinator's probe→rank→export→push ladder and
    finished by the adopter, is token-identical to the single-engine
    baseline — across slot AND paged layouts on both ends and the
    int8-always wire."""
    model, params = tiny
    src = _mk_engine(tiny, **src_kw)
    dst = _mk_engine(tiny, **dst_kw)
    wire = _Loopback()
    src_coord = DisaggCoordinator(src, _pipe(tiny), transport=wire)
    dst_coord = DisaggCoordinator(dst, _pipe(tiny), transport=wire)
    wire.peers["http://peer"] = dst_coord
    req = _prime(src)
    prefix = list(req.tokens)
    src.begin_drain()
    summary = src_coord.evacuate_all(["http://peer"])
    assert summary == {"lanes": 1, "adopted": 1, "fallback": 0,
                       "local_finish": 0}, name
    assert req.state == "evacuated"
    assert req.finish_reason == "evacuated"
    assert req.evac_target == "http://peer"
    assert req.done                     # the blocked POST wakes NOW
    # the source's journal keeps serving the committed prefix — the
    # router's resume consult reads exactly this after a later SIGKILL
    part = src.partial(req.request_id)
    assert part["state"] == "evacuated"
    assert part["evac_target"] == "http://peer"
    assert len(part["tokens"]) >= len(prefix) >= 1
    ref = _ref(model, params, _PROMPT, _MAX_NEW)
    assert part["tokens"] == ref[:len(part["tokens"])]
    assert _labelled(src_coord.registry.get(
        "fstpu_evac_lanes_total")) == {"adopted": 1}
    adopted = next(r for r in dst._slot_req if r is not None)
    dst.run_until_idle()
    assert adopted.state == "finished"
    assert adopted.tokens == ref, name


def test_evacuation_adds_zero_jitted_programs(tiny):
    """Evacuation rides the eager export/adopt path: after a drain
    with one live lane the source holds exactly its pinned program set
    and the adopter — which never prefilled — holds ONE decode program
    and nothing else."""
    src = _mk_engine(tiny)
    dst = _mk_engine(tiny)
    if not hasattr(src._decode_jit, "_cache_size"):
        pytest.skip("jit cache introspection unavailable")
    wire = _Loopback()
    src_coord = DisaggCoordinator(src, _pipe(tiny), transport=wire)
    wire.peers["http://peer"] = DisaggCoordinator(dst, _pipe(tiny))
    _prime(src)
    src.begin_drain()
    assert src_coord.evacuate_all(["http://peer"])["adopted"] == 1
    dst.run_until_idle()
    assert src._decode_jit._cache_size() == 1
    assert src._prefill_jit._cache_size() == 1   # one per bucket
    assert src._assign_jit._cache_size() == 1
    assert dst._decode_jit._cache_size() == 1
    assert dst._prefill_jit._cache_size() == 0   # adopt never prefills
    assert dst._assign_jit._cache_size() == 0


def test_begin_drain_flushes_queue_as_orderly_503(tiny):
    """Queued-but-unstarted requests must NOT wait out the drain: they
    reject NOW with reason "draining" (the API's orderly 503, so a
    router re-places them immediately) — without touching the pinned
    `rejected_draining` submit-refusal counter. The running lane keeps
    decoding: it is the evacuation candidate, not flush fodder."""
    eng = _mk_engine(tiny, num_slots=1)
    r1 = eng.submit(_PROMPT)
    eng.step()                          # r1 admitted and running
    r2 = eng.submit(_PROMPT)            # parked in the queue
    assert r2.state == "queued"
    eng.begin_drain()
    assert r2.state == "rejected"
    assert r2.finish_reason == "draining"
    assert r2.done                      # its blocked POST wakes NOW
    assert r1.state == "running"
    # the flush is not a submit refusal: the pinned counter only moves
    # when a NEW submission is turned away at the door
    assert eng.stats()["rejected_draining"] == 0
    with pytest.raises(Draining):
        eng.submit(_PROMPT)
    assert eng.stats()["rejected_draining"] == 1
    eng.run_until_idle()
    assert r1.state == "finished"


# ---- resume tier: resume-from-token-k + the commit journal --------------

@pytest.mark.parametrize("kw", [
    {}, PAGED, dict(kv_dtype="int8"), dict(kv_dtype="int8", **PAGED),
], ids=["fp32slot", "fp32paged", "int8slot", "int8paged"])
def test_resume_from_token_k_token_identity(tiny, kw):
    """A retried request carrying `resume_tokens` prefills
    prompt+prefix (all but the last resumed token, which the first
    tick re-commits) and decodes only the remainder — greedy output
    token-identical to the unkilled run for every cut point, across
    layouts and the int8 cache."""
    model, params = tiny
    ref = _ref(model, params, _PROMPT, _MAX_NEW)
    for k in (1, 3, 7):
        eng = _mk_engine(tiny, buckets=(8, 16), **kw)
        req = eng.submit(_PROMPT, resume_tokens=ref[:k],
                         resume_source="peer-a")
        eng.run_until_idle()
        assert req.state == "finished"
        assert req.tokens == ref, (kw, k)
        part = eng.partial(req.request_id)
        assert part["resumed_tokens"] == k
        assert part["resume_source"] == "peer-a"


def test_resume_adds_zero_jitted_programs(tiny):
    """The resume prefill rides the SAME bucketed prefill program as a
    fresh admission — recovering a request compiles nothing new."""
    model, params = tiny
    ref = _ref(model, params, _PROMPT, _MAX_NEW)
    eng = _mk_engine(tiny, buckets=(16,))
    if not hasattr(eng._decode_jit, "_cache_size"):
        pytest.skip("jit cache introspection unavailable")
    req = eng.submit(_PROMPT, resume_tokens=ref[:3])
    eng.run_until_idle()
    assert req.tokens == ref
    assert eng._decode_jit._cache_size() == 1
    assert eng._prefill_jit._cache_size() == 1
    assert eng._assign_jit._cache_size() == 1


def test_resume_validation(tiny):
    """A resume prefix that already covers the token budget leaves
    nothing to decode — a bad request field (422 at the API layer),
    never an engine wedge."""
    eng = _mk_engine(tiny)
    with pytest.raises(ValueError):
        eng.submit(_PROMPT, max_new_tokens=3, resume_tokens=[5, 6, 7])
    with pytest.raises(ValueError):
        eng.submit(_PROMPT, max_new_tokens=2, resume_tokens=[5, 6, 7])


def test_commit_journal_partial_and_ring_bound(tiny):
    """`partial()` serves finished snapshots (tokens + metadata) from
    a ring bounded by `journal_ring` — the oldest entry ages out, an
    unknown id is None, and a live lane's snapshot grows as it
    commits."""
    model, params = tiny
    eng = _mk_engine(tiny, journal_ring=2)
    ref = _ref(model, params, _PROMPT, _MAX_NEW)
    reqs = []
    for _ in range(3):
        r = eng.submit(_PROMPT)
        eng.run_until_idle()
        reqs.append(r)
    assert eng.partial(reqs[0].request_id) is None   # aged out
    assert eng.partial("never-ran") is None
    for r in reqs[1:]:
        part = eng.partial(r.request_id)
        assert part["state"] == "finished"
        assert part["tokens"] == ref
        assert part["generated_tokens"] == _MAX_NEW
    live = _prime(eng)
    part = eng.partial(live.request_id)
    assert part["state"] == "running"
    assert 1 <= len(part["tokens"]) < _MAX_NEW
    assert part["tokens"] == ref[:len(part["tokens"])]
    eng.run_until_idle()


# ---- HTTP tier: preempt fault, evacuation, SIGKILL resume ---------------

_HTTP_MAX_NEW = 24


def _start_replica(tiny, max_new, tick_delay_s=0.0):
    """One real stdlib replica (phase "both") with its coordinator.
    `tick_delay_s` throttles the decode tick so lanes are reliably
    mid-decode when the preemption notice lands (the tiny model is
    otherwise faster than any real one)."""
    from fengshen_tpu.api.main import (PipelineConfig, ServerConfig,
                                       build_stdlib_server)
    model, params = tiny
    pipe = _pipe(tiny, max_new)
    engine = ContinuousBatchingEngine(
        model, params,
        EngineConfig(num_slots=4, buckets=(8, 40), max_new_tokens=max_new,
                     max_queue=32, pad_token_id=0))
    engine.warmup()
    if tick_delay_s:
        real = engine._decode_jit

        def slow_decode(*a, **kw):
            time.sleep(tick_delay_s)
            return real(*a, **kw)

        engine._decode_jit = slow_decode
    engine.start()
    coord = DisaggCoordinator(engine, pipe)
    ready = threading.Event()
    ready.set()
    server = build_stdlib_server(
        ServerConfig(host="127.0.0.1", port=0, engine="continuous"),
        PipelineConfig(task="text_generation"), pipeline=pipe,
        engine=engine, ready=ready, draining=threading.Event(),
        disagg=coord)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    return server, engine, coord


def _stop_fleet(fleet):
    for server, engine, _ in fleet:
        server.shutdown()
        server.server_close()
        engine.stop()


def _route_many(router, prompts, width=4):
    texts = [" ".join(str(t) for t in p) for p in prompts]
    out = [None] * len(prompts)
    it = iter(range(len(prompts)))
    lock = threading.Lock()

    def worker():
        while True:
            with lock:
                i = next(it, None)
            if i is None:
                return
            out[i] = router.route_generate({"input_text": texts[i]})

    threads = [threading.Thread(target=worker) for _ in range(width)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return out


def _events(base, rid):
    with urllib.request.urlopen(
            f"http://{base}/debug/requests/{rid}", timeout=10) as r:
        wf = json.loads(r.read())
    return [e["event"] for e in wf["events"]]


def _resume_totals(router):
    return {k[0]: int(c.value) for k, c in router._c_resume.children()
            if c.value}


def _preempt_cb(engine, coord, peers, max_new=_HTTP_MAX_NEW):
    """The preemption notice, as `install_drain_handler`'s waiter
    delivers it: flush the queue, then evacuate the live lanes. Waits
    briefly for a lane that is EARLY in its decode — a drill landing
    in the admission window has nothing to rescue, and one landing on
    a nearly-finished lane loses the adoption race to the local tick
    loop (a legitimate `local_finish`, but not the outcome this test
    pins)."""

    def fire():
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            with engine._cv:
                early = [r for r in engine._slot_req
                         if r is not None and r.state == "running"
                         and 1 <= len(r.tokens) <= max_new - 16]
            if early:
                break
            time.sleep(0.005)
        engine.begin_drain()
        coord.evacuate_all(peers)

    return fire


def test_preempt_fault_evacuates_live_lanes_http(tiny):
    """THE integration pin (ISSUE 16): 2-replica fleet, `preempt`
    fault drains replica A at request index 4 mid-decode — every
    request answers 200, greedy token-identical to the undisturbed
    reference, at least one lane rode `fstpu_evac_lanes_total
    {outcome="adopted"}`, ZERO resume consults (evacuation answers
    through redirects, not regeneration), and both timelines show the
    rescue: terminal `evacuated` on the drained replica, `adopted` on
    the peer."""
    model, params = tiny
    fleet = [_start_replica(tiny, _HTTP_MAX_NEW, tick_delay_s=0.03)
             for _ in range(2)]
    targets = [f"127.0.0.1:{s.server_address[1]}"
               for s, *_ in fleet]
    plan = FleetFaultPlan(preempt_at={4: targets[0]})
    plan.preempt_with(targets[0], _preempt_cb(
        fleet[0][1], fleet[0][2], [f"http://{targets[1]}"]))
    transport = plan.wrap(UrllibTransport())
    router = FleetRouter(
        FleetConfig(replicas=targets, recovery_probes=1,
                    backoff_base_s=0.0, request_timeout_s=60.0),
        transport=transport, sleep=lambda s: None)
    transport.bind(router)
    try:
        router.poll_once()
        assert router.healthy_count() == 2
        rng = np.random.RandomState(3)
        prompts = [rng.randint(3, 96, 4 + (i % 3)).astype(np.int32)
                   for i in range(8)]
        out = _route_many(router, prompts, width=4)
        assert [code for code, _ in out] == [200] * len(prompts)
        refs = [" ".join(str(t) for t in
                         _ref(model, params, p, _HTTP_MAX_NEW))
                for p in prompts]
        assert [b["result"] for _, b in out] == refs
        assert plan.fired == [("preempt", 4, targets[0])]
        evac = _labelled(fleet[0][2].registry.get(
            "fstpu_evac_lanes_total"))
        assert evac.get("adopted", 0) >= 1, evac
        # drain-path rescue never consults the journal: nothing was
        # lost, so nothing resumes and nothing regenerates
        assert _resume_totals(router) == {}
        evac_rid = None
        for _, b in out:
            try:
                ev = _events(targets[0], b["request_id"])
            except urllib.error.HTTPError:
                continue
            if "evacuated" in ev:
                evac_rid = b["request_id"]
                assert ev[-1] == "evacuated"     # terminal event
                break
        assert evac_rid is not None
        peer_ev = _events(targets[1], evac_rid)
        assert "adopted" in peer_ev and "finished" in peer_ev
    finally:
        _stop_fleet(fleet)


def test_sigkill_adopter_resumes_from_journal_http(tiny):
    """The SIGKILL variant: A drains at index 4 and evacuates to B —
    then B goes dark (sticky transport kill) before its collects
    answer. The router's maybe-executed machinery consults the fleet's
    commit journals, reads the evacuated prefix off A (still draining,
    still serving `GET /partial/<id>`), and re-places the request on C
    with `resume_tokens` — every request 200, token-identical, at
    least one `fstpu_resume_total{outcome="resumed"}`, ZERO journal
    misses (nothing regenerated from token 0), `resumed_from` on C's
    timeline, and ONE assembled trace stitching A's and C's waterfalls
    under the same trace_id."""
    model, params = tiny
    fleet = [_start_replica(tiny, _HTTP_MAX_NEW, tick_delay_s=0.03)
             for _ in range(3)]
    targets = [f"127.0.0.1:{s.server_address[1]}"
               for s, *_ in fleet]
    a, b, c = targets
    plan = FleetFaultPlan(preempt_at={4: a}, kill_at={4: b})
    plan.preempt_with(a, _preempt_cb(
        fleet[0][1], fleet[0][2], [f"http://{b}"]))
    transport = plan.wrap(UrllibTransport())
    router = FleetRouter(
        FleetConfig(replicas=targets, recovery_probes=1,
                    backoff_base_s=0.0, request_timeout_s=60.0),
        transport=transport, sleep=lambda s: None)
    transport.bind(router)
    try:
        router.poll_once()
        assert router.healthy_count() == 3
        rng = np.random.RandomState(4)
        prompts = [rng.randint(3, 96, 4 + (i % 3)).astype(np.int32)
                   for i in range(10)]
        out = _route_many(router, prompts, width=5)
        assert [code for code, _ in out] == [200] * len(prompts)
        refs = [" ".join(str(t) for t in
                         _ref(model, params, p, _HTTP_MAX_NEW))
                for p in prompts]
        assert [b_["result"] for _, b_ in out] == refs
        assert ("preempt", 4, a) in plan.fired
        assert _labelled(fleet[0][2].registry.get(
            "fstpu_evac_lanes_total")).get("adopted", 0) >= 1
        resume = _resume_totals(router)
        assert resume.get("resumed", 0) >= 1, resume
        assert resume.get("miss", 0) == 0, resume
        # find the recovered request: resumed_from on C's timeline
        resumed_rid, resumed_body = None, None
        for _, body in out:
            try:
                ev = _events(c, body["request_id"])
            except urllib.error.HTTPError:
                continue
            if "resumed_from" in ev:
                resumed_rid, resumed_body = body["request_id"], body
                break
        assert resumed_rid is not None
        # the drained source still serves the journal it resumed from
        with urllib.request.urlopen(
                f"http://{a}/partial/{resumed_rid}", timeout=10) as r:
            part = json.loads(r.read())
        assert part["state"] == "evacuated"
        assert len(part["tokens"]) >= 1
        # the rescuer's journal holds the finished run, result decoded
        with urllib.request.urlopen(
                f"http://{c}/partial/{resumed_rid}", timeout=10) as r:
            part_c = json.loads(r.read())
        assert part_c["state"] == "finished"
        assert part_c["result"] == resumed_body["result"]
        assert _events(a, resumed_rid)[-1] == "evacuated"
        # ONE trace: the drained replica's waterfall and the rescuer's
        # joined under the same trace_id (the dead adopter degrades to
        # an error entry, never an unreadable trace)
        assembled = router.assemble(resumed_body["trace_id"])
        assert assembled is not None
        reps = assembled["replicas"]
        assert "waterfall" in reps[a] and "waterfall" in reps[c]
        assert reps[a]["waterfall"]["request_id"] == resumed_rid
        assert reps[c]["waterfall"]["request_id"] == resumed_rid
        a_ev = [e["event"]
                for e in reps[a]["waterfall"]["events"]]
        c_ev = [e["event"]
                for e in reps[c]["waterfall"]["events"]]
        assert "evacuated" in a_ev
        assert "resumed_from" in c_ev and "finished" in c_ev
    finally:
        _stop_fleet(fleet)
