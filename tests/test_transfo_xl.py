"""Transfo-XL denoise capability tests."""

import jax
import jax.numpy as jnp
import numpy as np


def test_denoise_collator():
    from fengshen_tpu.models.transfo_xl_denoise import DenoiseCollator

    class FakeTok:
        pad_token_id = 0
        eos_token_id = 1
        sep_token_id = 2

        def encode(self, text, add_special_tokens=True):
            return [3 + (ord(c) % 90) for c in text]

    coll = DenoiseCollator(FakeTok(), max_seq_length=32, drop_prob=0.3)
    batch = coll([{"text": "denoising autoencoder"}])
    assert batch["input_ids"].shape == (1, 32)
    labels = batch["labels"][0]
    # target half carries the ORIGINAL token ids after the separator
    orig = FakeTok().encode("denoising autoencoder")[:15]
    recon = labels[labels != -100]
    np.testing.assert_array_equal(recon, orig)


def test_segment_recurrence_matches_full_forward():
    from fengshen_tpu.models.transfo_xl_denoise import (
        TransfoXLDenoiseConfig, TransfoXLDenoiseModel)
    cfg = TransfoXLDenoiseConfig.small_test_config(dtype="float32")
    model = TransfoXLDenoiseModel(cfg)
    ids = jnp.asarray(np.random.RandomState(0).randint(3, 120, (1, 32)),
                      jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), ids)
    params = variables["params"]
    full = model.apply({"params": params}, ids)

    cache_vars = model.init(jax.random.PRNGKey(0),
                            jnp.zeros((1, 1), jnp.int32), init_cache=True)
    seg_logits, _ = model.apply(
        {"params": params, "cache": cache_vars["cache"]}, ids,
        deterministic=True, mutable=["cache"],
        method=TransfoXLDenoiseModel.forward_segments)
    np.testing.assert_allclose(np.asarray(seg_logits), np.asarray(full),
                               atol=1e-4)
