"""GPT-2 (Wenzhong) golden-value parity vs HF torch."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fengshen_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
from fengshen_tpu.models.gpt2.convert import torch_to_params


@pytest.fixture(scope="module")
def gpt2_pair():
    torch = pytest.importorskip("torch")
    import transformers

    hf_cfg = transformers.GPT2Config(
        vocab_size=128, n_positions=64, n_embd=32, n_layer=2, n_head=4,
        attn_implementation="eager")
    torch.manual_seed(0)
    tm = transformers.GPT2LMHeadModel(hf_cfg).eval()
    cfg = GPT2Config(vocab_size=128, n_positions=64, n_embd=32, n_layer=2,
                     n_head=4, dtype="float32")
    params = torch_to_params(tm.state_dict(), cfg)
    return params, tm, cfg


def test_gpt2_forward_parity(gpt2_pair):
    import torch
    params, tm, cfg = gpt2_pair
    ids = np.array([[3, 17, 9, 42, 7, 99, 1, 5]], dtype=np.int32)
    logits = GPT2LMHeadModel(cfg).apply({"params": params},
                                        jnp.asarray(ids))
    with torch.no_grad():
        ref = tm(torch.tensor(ids, dtype=torch.long)).logits.numpy()
    np.testing.assert_allclose(np.asarray(logits), ref, atol=2e-3)


def test_gpt2_greedy_generate_matches_hf(gpt2_pair):
    import torch
    from fengshen_tpu.utils.generate import generate
    params, tm, cfg = gpt2_pair
    prompt = np.array([[5, 11, 42, 7]], dtype=np.int64)
    with torch.no_grad():
        ref = tm.generate(torch.tensor(prompt), max_new_tokens=6,
                          do_sample=False,
                          pad_token_id=0).numpy()
    out = generate(GPT2LMHeadModel(cfg), params,
                   jnp.asarray(prompt, jnp.int32), max_new_tokens=6)
    np.testing.assert_array_equal(np.asarray(out)[0], ref[0])


def test_gpt2_sharded_matches_replicated(gpt2_pair, mesh8):
    params, _, cfg = gpt2_pair
    model = GPT2LMHeadModel(cfg)
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 127, (4, 16)),
                      jnp.int32)
    ref = model.apply({"params": params}, ids)
    from fengshen_tpu.parallel import make_shardings
    shardings = make_shardings(model.partition_rules(), params, mesh8)
    sharded = jax.device_put(params, shardings)
    out = jax.jit(lambda p, i: model.apply({"params": p}, i))(sharded, ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4)


def test_gpt2_scan_layers_parity(gpt2_pair):
    import dataclasses
    params, tm, cfg = gpt2_pair
    scan_cfg = dataclasses.replace(cfg, scan_layers=True)
    scan_params = torch_to_params(tm.state_dict(), scan_cfg)
    ids = np.array([[3, 17, 9, 42]], dtype=np.int32)
    ref = GPT2LMHeadModel(cfg).apply({"params": params}, jnp.asarray(ids))
    out = GPT2LMHeadModel(scan_cfg).apply({"params": scan_params},
                                          jnp.asarray(ids))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_gpt2_fused_ce_matches_plain(mesh8):
    """GPT2 (wte-tied head) through CausalLMModule's fused-CE path."""
    import argparse
    import dataclasses

    import jax
    import jax.numpy as jnp
    import numpy as np

    from fengshen_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
    from fengshen_tpu.parallel import MeshConfig, make_mesh, set_mesh
    from fengshen_tpu.trainer.modules import CausalLMModule

    base = GPT2Config(vocab_size=64, n_embd=32, n_layer=2, n_head=4,
                      n_positions=32, dtype="float32")
    args = argparse.Namespace(max_seq_length=16)
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 63, (2, 16)),
                      jnp.int32)
    batch = {"input_ids": ids}
    rng = jax.random.PRNGKey(0)

    plain = CausalLMModule(args, GPT2LMHeadModel(base), base)
    params = plain.init_params(rng)
    cfg_f = dataclasses.replace(base, fused_ce_chunks=4)
    fused = CausalLMModule(args, GPT2LMHeadModel(cfg_f), cfg_f)

    set_mesh(None)
    try:
        mesh1 = make_mesh(MeshConfig(data=8, fsdp=1, sequence=1,
                                     tensor=1))
        set_mesh(mesh1)
        l_p, _ = plain.training_loss(params, batch, rng)
        l_f, _ = fused.training_loss(params, batch, rng)
        assert abs(float(l_p - l_f)) < 1e-5
    finally:
        set_mesh(None)
