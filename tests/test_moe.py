"""Switch-MoE tests (beyond-reference capability; expert parallelism).

Covers: single-expert degeneracy (== plain SwiGLU up to dispatch fp32
round-trip), capacity-drop passthrough, aux-loss value at forced-uniform
and forced-collapsed routing, expert-parallel sharded training on the
virtual mesh, and the llama moe_experts wiring.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fengshen_tpu.ops import SwitchMoE, load_balancing_loss

pytestmark = pytest.mark.slow  # full-fit/e2e lane: run with -m slow or no -m filter


@pytest.fixture
def mesh_exp2():
    """1x1x2(expert)x1x1x2(tensor) mesh exercising expert parallelism."""
    from fengshen_tpu.parallel import MeshConfig, make_mesh, set_mesh
    mesh = make_mesh(MeshConfig(data=2, fsdp=1, expert=2, sequence=1,
                                tensor=2))
    set_mesh(mesh)
    yield mesh
    set_mesh(None)


def test_single_expert_is_dense_swiglu():
    # E=1: the router is a no-op (prob 1), capacity covers every token,
    # so the layer equals a plain SwiGLU MLP with the expert-0 tables
    moe = SwitchMoE(hidden_size=8, intermediate_size=16, num_experts=1,
                    capacity_factor=1.0, dtype=jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 6, 8))
    params = moe.init(jax.random.PRNGKey(1), x)["params"]
    out, aux = moe.apply({"params": params}, x)
    wg = params["experts_gate"][0]
    wu = params["experts_up"][0]
    wd = params["experts_down"][0]
    ref = (jax.nn.silu(x @ wg) * (x @ wu)) @ wd
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)
    np.testing.assert_allclose(float(aux), 1.0, atol=1e-6)  # E*1*1


def test_capacity_drop_passthrough_zero():
    # capacity so small that most tokens drop: dropped tokens contribute
    # exactly zero (the caller's residual carries them)
    moe = SwitchMoE(hidden_size=8, intermediate_size=16, num_experts=2,
                    capacity_factor=0.01, dtype=jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(0), (1, 16, 8))
    params = moe.init(jax.random.PRNGKey(1), x)["params"]
    out, _ = moe.apply({"params": params}, x)
    # capacity = ceil(16/2*0.01) = 1 per expert → ≥14 of 16 rows zero
    zero_rows = np.sum(np.all(np.asarray(out[0]) == 0.0, axis=-1))
    assert zero_rows >= 14


def test_load_balancing_loss_values():
    T, E = 64, 4
    # perfectly uniform hard routing + uniform probs → loss == 1
    probs = jnp.full((T, E), 1.0 / E)
    idx = jnp.asarray(np.arange(T) % E, jnp.int32)
    np.testing.assert_allclose(
        float(load_balancing_loss(probs, idx, E)), 1.0, atol=1e-6)
    # total collapse onto one expert with confident probs → loss == E
    probs = jnp.zeros((T, E)).at[:, 0].set(1.0)
    idx = jnp.zeros((T,), jnp.int32)
    np.testing.assert_allclose(
        float(load_balancing_loss(probs, idx, E)), float(E), atol=1e-6)


def test_moe_trains_sharded_with_expert_axis(mesh_exp2):
    """Expert-parallel training: jit a loss step with experts sharded over
    the 'expert' axis; loss must decrease and grads must flow through
    both the routed path and the router."""
    import optax
    from fengshen_tpu.parallel import (match_partition_rules,
                                       make_shardings)
    from fengshen_tpu.ops.moe import MOE_PARTITION_RULES

    moe = SwitchMoE(hidden_size=8, intermediate_size=16, num_experts=4,
                    capacity_factor=2.0, dtype=jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 8, 8))
    y = jax.random.normal(jax.random.PRNGKey(1), (4, 8, 8))
    params = moe.init(jax.random.PRNGKey(2), x)["params"]
    specs = match_partition_rules(
        MOE_PARTITION_RULES + [(".*", None)], params)
    shardings = make_shardings(specs, params, mesh_exp2)
    params = jax.device_put(params, shardings)
    tx = optax.adam(3e-3)
    ost = tx.init(params)

    @jax.jit
    def step(p, o, x, y):
        def loss_fn(p):
            out, aux = moe.apply({"params": p}, x)
            return jnp.mean((out - y) ** 2) + 0.01 * aux
        l, g = jax.value_and_grad(loss_fn)(p)
        u, o = tx.update(g, o)
        return optax.apply_updates(p, u), o, l

    losses = []
    for _ in range(60):
        params, ost, l = step(params, ost, x, y)
        losses.append(float(l))
    assert losses[-1] < losses[0] * 0.8, losses


def test_llama_moe_wiring(mesh_exp2):
    """cfg.moe_experts routes the decoder MLP through SwitchMoE; forward
    works under jit on the expert mesh and the aux loss is sowable."""
    from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig(vocab_size=64, hidden_size=16, intermediate_size=32,
                      num_hidden_layers=2, num_attention_heads=2,
                      num_key_value_heads=2, max_position_embeddings=16,
                      dtype="float32", moe_experts=4)
    model = LlamaForCausalLM(cfg)
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 64, (2, 8)),
                      jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), ids)
    assert "experts_gate" in str(jax.tree_util.tree_structure(
        variables["params"]))
    # pass params only: init's own sowed losses must not accumulate
    logits, state = model.apply({"params": variables["params"]}, ids,
                                mutable=["losses"])
    assert logits.shape == (2, 8, 64)
    aux = jax.tree_util.tree_leaves(state["losses"])
    assert len(aux) == cfg.num_hidden_layers
    for a in aux:
        assert float(a) >= 1.0 - 1e-5  # load-balance loss lower bound


def test_moe_pad_tokens_excluded():
    """Pads must not claim capacity or skew the aux loss: with tight
    capacity, all real tokens keep their slots when half the batch is
    padding, and pad outputs are exactly zero."""
    moe = SwitchMoE(hidden_size=8, intermediate_size=16, num_experts=2,
                    capacity_factor=1.0, dtype=jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(0), (1, 16, 8))
    mask = jnp.asarray([[1] * 8 + [0] * 8], jnp.int32)
    params = moe.init(jax.random.PRNGKey(1), x)["params"]
    out_m, aux_m = moe.apply({"params": params}, x, token_mask=mask)
    # pad rows exactly zero
    np.testing.assert_allclose(np.asarray(out_m[0, 8:]), 0.0)
    # valid rows equal the unpadded run of just those tokens (capacity
    # ceil(16/2*1.0)=8 covers all 8 real tokens in both runs)
    out_u, aux_u = moe.apply({"params": params}, x[:, :8])
    np.testing.assert_allclose(np.asarray(out_m[0, :8]),
                               np.asarray(out_u[0]), atol=1e-4)
    np.testing.assert_allclose(float(aux_m), float(aux_u), atol=1e-6)


def test_llama_moe_scan_layers_losses_survive():
    """scan_layers=True must still expose the sowed aux losses (stacked
    along the layer axis by nn.scan)."""
    from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig(vocab_size=64, hidden_size=16, intermediate_size=32,
                      num_hidden_layers=3, num_attention_heads=2,
                      num_key_value_heads=2, max_position_embeddings=16,
                      dtype="float32", moe_experts=4, scan_layers=True)
    model = LlamaForCausalLM(cfg)
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 64, (2, 8)),
                      jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), ids)
    logits, state = model.apply({"params": variables["params"]}, ids,
                                mutable=["losses"])
    leaves = jax.tree_util.tree_leaves(state["losses"])
    assert leaves, "losses collection dropped under nn.scan"
    stacked = leaves[0]
    assert stacked.shape[0] == cfg.num_hidden_layers
    assert float(stacked.min()) >= 1.0 - 1e-5


def test_llama_moe_cached_decode():
    """Cached generation with a MoE llama: the decode step feeds 1-token
    hidden states with the full-prompt attention mask — the layer must not
    try to reshape the mask onto the 1-token batch (regression)."""
    from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from fengshen_tpu.utils.generate import generate

    cfg = LlamaConfig(vocab_size=64, hidden_size=16, intermediate_size=32,
                      num_hidden_layers=2, num_attention_heads=2,
                      num_key_value_heads=2, max_position_embeddings=32,
                      dtype="float32", moe_experts=2)
    model = LlamaForCausalLM(cfg)
    ids = jnp.asarray(np.random.RandomState(1).randint(0, 64, (2, 6)),
                      jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids)["params"]
    out = generate(model, params, ids, max_new_tokens=4)
    assert out.shape == (2, 10)


def test_causal_lm_module_collects_moe_aux():
    """CausalLMModule.training_loss must fold the sowed load-balance loss
    into the objective (weighted by cfg.moe_aux_weight) and report it
    (regression: the sow used to be silently dropped)."""
    import argparse

    from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from fengshen_tpu.trainer.modules import CausalLMModule

    cfg = LlamaConfig(vocab_size=64, hidden_size=16, intermediate_size=32,
                      num_hidden_layers=2, num_attention_heads=2,
                      num_key_value_heads=2, max_position_embeddings=16,
                      dtype="float32", moe_experts=4, moe_aux_weight=0.5)
    model = LlamaForCausalLM(cfg)
    module = CausalLMModule(argparse.Namespace(), model, cfg)
    ids = jnp.asarray(np.random.RandomState(2).randint(0, 64, (2, 8)),
                      jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids)["params"]
    batch = {"input_ids": ids}
    loss, metrics = module.training_loss(params, batch,
                                         jax.random.PRNGKey(1))
    assert "aux_loss" in metrics
    aux = float(metrics["aux_loss"])
    assert aux >= cfg.num_hidden_layers * (1.0 - 1e-5)
    # the weighted aux is part of the loss: recompute without it
    logits = model.apply({"params": params}, ids)
    from fengshen_tpu.parallel.cross_entropy import \
        vocab_parallel_cross_entropy
    ce, _ = vocab_parallel_cross_entropy(logits[:, :-1], ids[:, 1:])
    np.testing.assert_allclose(float(loss), float(ce) + 0.5 * aux,
                               rtol=1e-5)
