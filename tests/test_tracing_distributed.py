"""Fleet-wide distributed tracing (ISSUE 11, docs/observability.md
"Distributed tracing"): trace-context propagation, cross-process
waterfall assembly, and Perfetto-exportable trace bundles.

Three tiers:

- UNIT: traceparent parse/format round-trips, seeded id determinism,
  the span ledger's bounded record, and the router's span ledger over
  a fake transport (admit/placement/attempt spans, retries as sibling
  children of one trace, the `fstpu_fleet_attempt_seconds{outcome}`
  histogram, traceparent propagated to replicas as body field + lifted
  from the header);
- ASSEMBLY: `/debug/traces/<id>` stitches the router ledger with the
  involved replicas' waterfalls — clock anchoring with skew REPORTED,
  fetch failures degrading to error entries, byte-identical JSON
  across PYTHONHASHSEED in a jax-free subprocess (like `/fleet`), and
  `traceview` emitting valid Chrome trace-event JSON;
- INTEGRATION (tiny llama, real stdlib replicas): the acceptance pin —
  a FleetFaultPlan fault at a chosen request index yields ONE assembled
  trace whose ledger shows attempt 1 (failed, faulted replica) +
  attempt 2 (ok, surviving replica) as children of the same trace_id,
  per-process waterfalls attached with phases summing exactly, and
  greedy outputs token-identical with tracing on (one decode compile —
  trace bookkeeping adds no traced-code inputs).
"""

import json
import os
import subprocess
import sys
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from fengshen_tpu.fleet import (FleetConfig, FleetFaultPlan,
                                FleetRouter, TransportError,
                                UrllibTransport)
from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from fengshen_tpu.observability import (FlightRecorder, SpanLedger,
                                        TraceContext, TraceIds,
                                        parse_traceparent)
from fengshen_tpu.observability.traceview import chrome_trace
from fengshen_tpu.serving import ContinuousBatchingEngine, EngineConfig
from fengshen_tpu.utils.generate import generate

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---- trace context units ------------------------------------------------

def test_traceparent_round_trip_and_rejects():
    ctx = TraceContext(trace_id="ab" * 16, span_id="cd" * 8)
    assert ctx.to_traceparent() == f"00-{'ab' * 16}-{'cd' * 8}-01"
    back = parse_traceparent(ctx.to_traceparent())
    assert back == ctx
    # malformed inputs degrade to None (fresh trace), never raise
    for bad in (None, 17, "", "00-zz-cd-01",
                f"ff-{'ab' * 16}-{'cd' * 8}-01",          # version ff
                f"zz-{'ab' * 16}-{'cd' * 8}-01",          # non-hex ver
                f"00-{'0' * 32}-{'cd' * 8}-01",           # zero trace
                f"00-{'ab' * 16}-{'0' * 16}-01",          # zero span
                f"00-{'ab' * 15}-{'cd' * 8}-01",          # short trace
                "no-dashes-here"):
        assert parse_traceparent(bad) is None, bad


def test_trace_ids_seeded_deterministic():
    a, b = TraceIds(seed=7), TraceIds(seed=7)
    assert [a.trace_id() for _ in range(3)] == \
        [b.trace_id() for _ in range(3)]
    assert a.span_id() == b.span_id()
    # UNSEEDED mints must not collide (OS entropy, the production
    # default): two routers with the same config draw distinct ids
    assert TraceIds().trace_id() != TraceIds().trace_id()
    tid = TraceIds(seed=0).trace_id()
    assert len(tid) == 32 and set(tid) <= set("0123456789abcdef")
    assert parse_traceparent(
        TraceContext(tid, TraceIds(seed=0).span_id())
        .to_traceparent()) is not None


def test_span_ledger_records_and_bounds():
    t = [100.0]
    ledger = SpanLedger("router", clock=lambda: t[0],
                        wall=lambda: 5000.25, max_traces=2,
                        ids=TraceIds(seed=0))
    ctx = ledger.start_trace("fleet/request", request_id="r-0")
    t[0] += 0.5
    child = ledger.start_span(ctx.trace_id, "router/attempt",
                              ctx.span_id, replica="a:1")
    t[0] += 0.25
    ledger.end_span(ctx.trace_id, child, outcome="ok", status=200)
    trace = ledger.get_trace(ctx.trace_id)
    assert trace["service"] == "router"
    assert trace["epoch_unix_s"] == 5000.25
    root, att = trace["spans"]
    assert root["name"] == "fleet/request"
    assert root["parent_span_id"] is None
    assert root["attrs"]["request_id"] == "r-0"
    assert att["parent_span_id"] == root["span_id"]
    assert att["t_start_s"] == 0.5 and att["duration_s"] == 0.25
    assert att["attrs"] == {"replica": "a:1", "outcome": "ok",
                            "status": 200}
    # bounded: a third trace evicts the oldest
    ledger.start_trace("fleet/request")
    ledger.start_trace("fleet/request")
    assert ledger.get_trace(ctx.trace_id) is None
    assert len(ledger.provider()["traces"]) == 2
    # unknown trace: recording degrades to no-ops, never raises
    assert ledger.start_span("f" * 32, "x", None) is None
    ledger.end_span("f" * 32, "deadbeefdeadbeef")


def test_span_ledger_caps_spans_per_trace():
    """A client may legally reuse ONE traceparent across many requests;
    joining must not grow a single record without bound — past the cap
    spans are dropped (start_span -> None, so end_span no-ops) and
    counted in the rendered trace."""
    ledger = SpanLedger("router", max_spans_per_trace=3,
                        ids=TraceIds(seed=0))
    ctx = ledger.start_trace("fleet/request")
    assert ledger.start_span(ctx.trace_id, "a", ctx.span_id) is not None
    assert ledger.start_span(ctx.trace_id, "b", ctx.span_id) is not None
    assert ledger.start_span(ctx.trace_id, "c", ctx.span_id) is None
    # joining the same trace id past the cap still returns a usable
    # context (propagation keeps working) but records nothing more
    ctx2 = ledger.start_trace("fleet/request", trace_id=ctx.trace_id)
    assert ctx2.trace_id == ctx.trace_id
    trace = ledger.get_trace(ctx.trace_id)
    assert len(trace["spans"]) == 3
    assert trace["spans_dropped"] == 2
    # an uncapped trace never carries the key
    other = ledger.start_trace("fleet/request")
    assert "spans_dropped" not in ledger.get_trace(other.trace_id)


# ---- router ledger over a fake transport --------------------------------

class ManualClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


class FakeReplica:
    def __init__(self, num_slots: int = 4):
        self.healthz = (200, {"status": "ok", "ready": True})
        self.stats = {"slots_active": 0, "queue_depth": 0,
                      "num_slots": num_slots, "draining": False}
        self.fail = None
        self.generate_code = 200
        self.requests = []
        #: request_id -> the /debug/requests/<id> payload to answer
        self.waterfalls = {}

    def response(self, body):
        return self.generate_code, {
            "result": "ok", "request_id": body.get("request_id"),
            "finish_reason": "length"}


class FakeTransport:
    def __init__(self, replicas):
        self.replicas = replicas

    def request(self, base_url, method, path, body, timeout_s):
        rep = self.replicas[base_url.split("://", 1)[1]]
        if rep.fail is not None:
            raise TransportError(f"injected {rep.fail}",
                                 sent=rep.fail == "timeout")
        if path == "/healthz":
            return rep.healthz
        if path == "/stats":
            return 200, rep.stats
        if path.startswith("/debug/requests/"):
            rid = path[len("/debug/requests/"):]
            if rid in rep.waterfalls:
                return 200, rep.waterfalls[rid]
            return 404, {"error": "unknown"}
        if method == "POST" and path.startswith("/api/"):
            rep.requests.append(body)
            return rep.response(body)
        return 404, {}


def _mk_router(names, replicas, clock=None, **cfg):
    cfg.setdefault("recovery_probes", 1)
    cfg.setdefault("backoff_base_s", 0.05)
    cfg.setdefault("seed", 0)
    cfg.setdefault("trace_seed", 0)
    return FleetRouter(
        FleetConfig(replicas=names, **cfg),
        transport=FakeTransport(replicas),
        clock=clock or ManualClock(), sleep=lambda s: None,
        wall=lambda: 7000.0)


def test_router_spans_and_propagation_on_retry():
    """One retried request = ONE trace: placement + attempt spans as
    children of the root, the failed attempt carrying outcome/backoff,
    the traceparent body field parented to EACH attempt's own span, and
    the per-attempt histogram labelled by outcome."""
    reps = {"a:1": FakeReplica(), "b:2": FakeReplica()}
    router = _mk_router(("a:1", "b:2"), reps, breaker_threshold=1,
                        max_retries=2, backoff_base_s=0.1)
    router.poll_once()
    reps["a:1"].fail = "connect"
    code, body = router.route_generate({"input_text": "1"})
    assert code == 200
    tid = body["trace_id"]
    trace = router.tracer.get_trace(tid)
    assert trace is not None and trace["trace_id"] == tid
    by_name = {}
    for span in trace["spans"]:
        by_name.setdefault(span["name"], []).append(span)
    root = by_name["fleet/request"][0]
    assert root["attrs"]["request_id"] == body["request_id"]
    assert root["attrs"]["outcome"] == "ok"
    assert root["attrs"]["attempts"] == 2
    assert root["duration_s"] is not None
    # every non-root span is a CHILD of the root
    for name in ("router/enqueue", "router/placement",
                 "router/attempt"):
        for span in by_name[name]:
            assert span["parent_span_id"] == root["span_id"]
    att1, att2 = by_name["router/attempt"]
    assert att1["attrs"]["replica"] == "a:1"
    assert att1["attrs"]["outcome"] == "connect"
    assert 0.05 <= att1["attrs"]["backoff_s"] < 0.1   # jittered
    assert att2["attrs"]["replica"] == "b:2"
    assert att2["attrs"]["outcome"] == "ok"
    assert att2["attrs"]["status"] == 200
    assert [p["attrs"]["replica"]
            for p in by_name["router/placement"]] == ["a:1", "b:2"]
    # the replica saw a traceparent parented to ITS attempt span
    sent = reps["b:2"].requests[0]
    ctx = parse_traceparent(sent["traceparent"])
    assert ctx.trace_id == tid and ctx.span_id == att2["span_id"]
    # per-attempt seconds landed under both outcome labels
    hist = router.registry.get("fstpu_fleet_attempt_seconds")
    outcomes = {values[0]: child.count
                for values, child in hist.children()}
    assert outcomes == {"connect": 1, "ok": 1}
    assert int(router.registry.get(
        "fstpu_trace_started_total").value()) == 1


def test_router_joins_incoming_traceparent():
    """An upstream traceparent is JOINED (same trace id, root parented
    to the caller's span), not replaced — routers stack."""
    reps = {"a:1": FakeReplica()}
    router = _mk_router(("a:1",), reps)
    router.poll_once()
    upstream = TraceContext("ab" * 16, "cd" * 8)
    code, body = router.route_generate(
        {"input_text": "1", "traceparent": upstream.to_traceparent()})
    assert code == 200 and body["trace_id"] == upstream.trace_id
    trace = router.tracer.get_trace(upstream.trace_id)
    root = trace["spans"][0]
    assert root["name"] == "fleet/request"
    assert root["parent_span_id"] == upstream.span_id


def test_fleet_state_poll_staleness_fields():
    """Satellite: /fleet carries per-replica last_poll_age_s (None
    until the first completed poll, then the age on the router clock)
    and a top-level consecutive_failures."""
    clock = ManualClock()
    reps = {"a:1": FakeReplica(), "b:2": FakeReplica()}
    router = _mk_router(("a:1", "b:2"), reps, clock=clock,
                        breaker_threshold=3)
    state = {r["name"]: r for r in router.fleet_state()["replicas"]}
    assert state["a:1"]["last_poll_age_s"] is None
    assert state["a:1"]["consecutive_failures"] == 0
    router.poll_once()
    clock.advance(2.5)
    state = {r["name"]: r for r in router.fleet_state()["replicas"]}
    assert state["a:1"]["last_poll_age_s"] == 2.5
    assert state["b:2"]["last_poll_age_s"] == 2.5
    # an unreachable replica still counts as POLLED (the sweep ran);
    # its failure streak is the visible signal
    reps["b:2"].fail = "connect"
    router.poll_once()
    state = {r["name"]: r for r in router.fleet_state()["replicas"]}
    assert state["b:2"]["last_poll_age_s"] == 0.0
    assert state["b:2"]["consecutive_failures"] == 1


# ---- assembly -----------------------------------------------------------

def _waterfall(rid, epoch, total=0.6):
    return {"request_id": rid, "state": "finished",
            "finish_reason": "length", "prompt_tokens": 3,
            "generated_tokens": 4, "slot": 0, "ttft_s": 0.3,
            "phases": {"queue_wait_s": 0.1, "prefill_s": 0.2,
                       "decode_s": round(total - 0.3, 6),
                       "decode_stall_s": 0.0, "total_s": total},
            "events": [{"t_s": 0.0, "event": "enqueued"},
                       {"t_s": total, "event": "finished",
                        "reason": "length"}],
            "dropped_events": 0, "trace_id": None,
            "parent_span_id": None, "epoch_unix_s": epoch}


def test_assemble_attaches_waterfalls_with_skew():
    """Assembly stitches the ledger with each involved replica's
    waterfall; the clock anchoring reports offset + skew instead of
    hiding them; a failed attempt's replica still appears (as an error
    entry when unreachable)."""
    reps = {"a:1": FakeReplica(), "b:2": FakeReplica()}
    router = _mk_router(("a:1", "b:2"), reps, breaker_threshold=1,
                        max_retries=1)
    router.poll_once()
    reps["a:1"].fail = "connect"
    code, body = router.route_generate({"input_text": "1"})
    assert code == 200
    rid, tid = body["request_id"], body["trace_id"]
    # router wall anchor is 7000.0; the surviving replica anchors 0.4s
    # later — that offset must surface, not vanish
    reps["b:2"].waterfalls[rid] = _waterfall(rid, 7000.4)
    assembled = router.assemble(tid)
    assert assembled["trace_id"] == tid
    assert assembled["request_id"] == rid
    assert sorted(assembled["replicas"]) == ["a:1", "b:2"]
    a, b = assembled["replicas"]["a:1"], assembled["replicas"]["b:2"]
    assert a["error"].startswith("unreachable")
    assert "waterfall" not in a
    assert b["waterfall"]["request_id"] == rid
    assert b["offset_in_trace_s"] == 0.4
    # manual clock: the attempt dispatched at t_start 0.0, so skew ==
    # offset here
    assert b["clock_skew_s"] == 0.4
    ph = b["waterfall"]["phases"]
    assert abs(ph["queue_wait_s"] + ph["prefill_s"] + ph["decode_s"]
               - ph["total_s"]) < 1e-9
    # unknown trace ids answer None (404 at the server layer)
    assert router.assemble("9" * 32) is None
    reg = router.registry
    assert int(reg.get("fstpu_trace_assembled_total").value()) == 1
    assert int(reg.get("fstpu_trace_fetch_errors_total").value()) == 1


def test_assemble_joined_trace_fetches_per_request():
    """One caller traceparent reused across TWO requests (W3C-legal):
    each attempt span records its OWN request_id, so assembly fetches
    every replica's actual request — never the first id the trace ever
    saw (which would 404 on replicas that served later requests)."""
    clock = ManualClock()
    reps = {"a:1": FakeReplica(), "b:2": FakeReplica()}
    router = _mk_router(("a:1", "b:2"), reps, clock=clock)
    router.poll_once()
    tp = TraceContext("ab" * 16, "cd" * 8).to_traceparent()
    code, b1 = router.route_generate(
        {"input_text": "1", "traceparent": tp, "request_id": "r-1"})
    assert code == 200
    # second request lands on the OTHER replica (a:1 now looks busy)
    reps["a:1"].stats["slots_active"] = 4
    router.poll_once()
    code, b2 = router.route_generate(
        {"input_text": "2", "traceparent": tp, "request_id": "r-2"})
    assert code == 200
    assert b1["trace_id"] == b2["trace_id"] == "ab" * 16
    reps["a:1"].waterfalls["r-1"] = _waterfall("r-1", 7000.1)
    reps["b:2"].waterfalls["r-2"] = _waterfall("r-2", 7000.2)
    assembled = router.assemble("ab" * 16)
    assert sorted(assembled["replicas"]) == ["a:1", "b:2"]
    assert assembled["replicas"]["a:1"]["waterfall"][
        "request_id"] == "r-1"
    assert assembled["replicas"]["b:2"]["waterfall"][
        "request_id"] == "r-2"
    assert int(router.registry.get(
        "fstpu_trace_fetch_errors_total").value()) == 0
    # a THIRD request on the same trace landing on b:2 again: one
    # attachment per replica (its first request), the later one NAMED
    # rather than silently invisible
    code, b3 = router.route_generate(
        {"input_text": "3", "traceparent": tp, "request_id": "r-3"})
    assert code == 200
    assembled = router.assemble("ab" * 16)
    b = assembled["replicas"]["b:2"]
    assert b["waterfall"]["request_id"] == "r-2"
    assert b["other_request_ids"] == ["r-3"]
    assert "other_request_ids" not in assembled["replicas"]["a:1"]


def test_assembled_trace_deterministic_across_hashseed(tmp_path):
    """The `/debug/traces/<id>` payload (sorted JSON) is byte-identical
    across PYTHONHASHSEED — seeded ids, injected clocks, explicit
    request id. Pure-stdlib subprocess: the fleet package AND the new
    tracing modules must not pull jax."""
    script = """
import json, sys
assert "jax" not in sys.modules
from fengshen_tpu.fleet import FleetConfig, FleetRouter, TransportError
from fengshen_tpu.observability.tracectx import SpanLedger, TraceIds
from fengshen_tpu.observability.traceview import chrome_trace
assert "jax" not in sys.modules, "tracing tier must stay jax-free"

class Clock:
    def __call__(self): return 100.0

WATERFALL = {"request_id": "req-pin", "state": "finished",
             "phases": {"queue_wait_s": 0.1, "prefill_s": 0.2,
                        "decode_s": 0.3, "decode_stall_s": 0.0,
                        "total_s": 0.6},
             "events": [{"t_s": 0.0, "event": "enqueued"},
                        {"t_s": 0.6, "event": "finished"}],
             "dropped_events": 0, "epoch_unix_s": 1000.25}

class T:
    def request(self, base_url, method, path, body, timeout_s):
        if base_url.endswith(":1"):
            if path == "/healthz": return 200, {"ready": True}
            if path == "/stats": return 200, {"slots_active": 0,
                                              "num_slots": 4,
                                              "queue_depth": 0}
            if path.startswith("/debug/requests/"):
                return 200, dict(WATERFALL)
            return 200, {"result": "ok",
                         "request_id": body["request_id"]}
        raise TransportError("dead", sent=False)

r = FleetRouter(FleetConfig(replicas=("a:1", "b:2"),
                            recovery_probes=1, breaker_threshold=1,
                            backoff_base_s=0.0, max_retries=1,
                            trace_seed=0),
                transport=T(), clock=Clock(), sleep=lambda s: None,
                wall=lambda: 1000.0)
r.poll_once()
code, body = r.route_generate({"input_text": "1",
                               "request_id": "req-pin"})
assert code == 200, code
assembled = r.assemble(body["trace_id"])
print(json.dumps(assembled, sort_keys=True))
print(json.dumps(chrome_trace(assembled), sort_keys=True))
"""
    outs = []
    for seed in ("0", "1"):
        out = subprocess.run(
            [sys.executable, "-c", script],
            env={**os.environ, "PYTHONHASHSEED": seed},
            capture_output=True, text=True, cwd=REPO)
        assert out.returncode == 0, out.stderr
        outs.append(out.stdout)
    assert outs[0] == outs[1]
    assembled = json.loads(outs[0].splitlines()[0])
    assert assembled["request_id"] == "req-pin"
    assert assembled["replicas"]["a:1"]["waterfall"]["state"] == \
        "finished"


# ---- traceview ----------------------------------------------------------

def _validate_chrome(doc):
    """The Chrome trace-event JSON-object-format contract: a
    traceEvents list whose entries carry name/ph/ts/pid (+ dur on X)."""
    assert isinstance(doc["traceEvents"], list) and doc["traceEvents"]
    assert doc["displayTimeUnit"] == "ms"
    for ev in doc["traceEvents"]:
        for key in ("name", "ph", "pid", "tid"):
            assert key in ev, ev
        assert ev["ph"] in ("X", "M", "i"), ev
        if ev["ph"] != "M":
            assert isinstance(ev["ts"], int) and ev["ts"] >= 0
        if ev["ph"] == "X":
            assert isinstance(ev["dur"], int) and ev["dur"] >= 0


def test_traceview_converts_assembled_trace(tmp_path):
    reps = {"a:1": FakeReplica()}
    router = _mk_router(("a:1",), reps)
    router.poll_once()
    code, body = router.route_generate({"input_text": "1"})
    rid = body["request_id"]
    # replica clock runs BEHIND the router's: events would go negative
    # without the shift the converter applies
    reps["a:1"].waterfalls[rid] = _waterfall(rid, 6999.5)
    assembled = router.assemble(body["trace_id"])
    doc = chrome_trace(assembled)
    _validate_chrome(doc)
    names = {e["name"] for e in doc["traceEvents"]}
    assert {"fleet/request", "router/attempt", "queue_wait",
            "prefill", "decode", "process_name"} <= names
    procs = {e["args"]["name"] for e in doc["traceEvents"]
             if e["ph"] == "M"}
    assert procs == {"router", "a:1"}
    assert doc["otherData"]["shifted_us"] == 500_000

    # the CLI round-trips a saved assembled trace deterministically
    path = tmp_path / "assembled.json"
    path.write_text(json.dumps(assembled, sort_keys=True))
    outs = []
    for seed in ("0", "1"):
        out = subprocess.run(
            [sys.executable, "-m",
             "fengshen_tpu.observability.traceview", str(path)],
            env={**os.environ, "PYTHONHASHSEED": seed,
                 "JAX_PLATFORMS": "cpu"},
            capture_output=True, text=True, cwd=REPO)
        assert out.returncode == 0, out.stderr
        outs.append(out.stdout)
    assert outs[0] == outs[1]
    _validate_chrome(json.loads(outs[0]))
    # missing input exits 2
    assert subprocess.run(
        [sys.executable, "-m",
         "fengshen_tpu.observability.traceview",
         str(tmp_path / "nope.json")],
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        capture_output=True, text=True, cwd=REPO).returncode == 2


def test_traceview_renders_fetch_error_attachment():
    """A dead replica's {"error": ...} attachment must surface the
    diagnostic in the export — an instant mark carrying the error, not
    a healthy-looking track of zero-width phase bars."""
    doc = {"schema": 1, "trace_id": "f" * 32, "request_id": "r-1",
           "router": {"trace_id": "f" * 32, "service": "router",
                      "epoch_unix_s": 7000.0, "spans": []},
           "replicas": {"a:1": {"error": "unreachable: injected"}}}
    out = chrome_trace(doc)
    evs = [e for e in out["traceEvents"] if e["ph"] != "M"]
    assert [e["name"] for e in evs] == ["fetch_error"]
    assert evs[0]["args"]["error"] == "unreachable: injected"
    assert not [e for e in out["traceEvents"] if e["ph"] == "X"]


def test_traceview_reads_flight_recorder_bundle(tmp_path):
    """Satellite: a router wired to a FlightRecorder contributes
    traces.json to every bundle, and traceview converts the bundle
    directory directly."""
    rec = FlightRecorder(dump_dir=str(tmp_path))
    reps = {"a:1": FakeReplica()}
    router = FleetRouter(
        FleetConfig(replicas=("a:1",), recovery_probes=1),
        transport=FakeTransport(reps), clock=ManualClock(),
        sleep=lambda s: None, wall=lambda: 7000.0, recorder=rec)
    router.poll_once()
    code, body = router.route_generate({"input_text": "1"})
    assert code == 200
    bundle = rec.dump(reason="test")
    traces = json.loads(
        open(os.path.join(bundle, "traces.json")).read())
    assert traces["service"] == "router"
    assert [t["trace_id"] for t in traces["traces"]] == \
        [body["trace_id"]]
    # router events rode along in the ring too
    events = [json.loads(line) for line in
              open(os.path.join(bundle, "events.jsonl"))]
    assert any(e.get("event") == "fleet_replica_in" for e in events)
    out = subprocess.run(
        [sys.executable, "-m",
         "fengshen_tpu.observability.traceview", bundle],
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        capture_output=True, text=True, cwd=REPO)
    assert out.returncode == 0, out.stderr
    _validate_chrome(json.loads(out.stdout))


# ---- fleet server surface -----------------------------------------------

def test_fleet_server_traces_endpoint_and_http_timing():
    """GET /debug/traces/<id> serves the assembled trace (404 on
    unknown ids), and the router's own endpoints land in the SAME
    fstpu_http_request_seconds{route} histogram the replica servers
    feed (satellite)."""
    from fengshen_tpu.fleet import build_fleet_server
    from fengshen_tpu.observability import get_registry

    reps = {"a:1": FakeReplica()}
    router = _mk_router(("a:1",), reps)
    router.poll_once()
    code, body = router.route_generate({"input_text": "1"})
    rid = body["request_id"]
    reps["a:1"].waterfalls[rid] = _waterfall(rid, 7000.1)
    server = build_fleet_server(router, host="127.0.0.1", port=0)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{port}"
    try:
        with urllib.request.urlopen(
                f"{base}/debug/traces/{body['trace_id']}",
                timeout=10) as r:
            assembled = json.loads(r.read())
        assert assembled["trace_id"] == body["trace_id"]
        assert assembled["replicas"]["a:1"]["waterfall"][
            "request_id"] == rid
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(
                f"{base}/debug/traces/{'9' * 32}", timeout=10)
        assert exc.value.code == 404
        with urllib.request.urlopen(f"{base}/healthz", timeout=10):
            pass
        with urllib.request.urlopen(f"{base}/fleet", timeout=10) as r:
            fleet = json.loads(r.read())
        assert fleet["replicas"][0]["last_poll_age_s"] is not None
        with urllib.request.urlopen(f"{base}/metrics", timeout=10) as r:
            text = r.read().decode()
        # the router's own endpoint latency + the tracing tier's
        # counters render beside the fleet gauges
        assert 'fstpu_http_request_seconds_bucket' in text
        assert 'route="/healthz"' in text
        assert 'route="/debug/traces/<id>"' in text
        assert 'fstpu_fleet_attempt_seconds_bucket' in text
        assert 'fstpu_trace_started_total' in text
        hist = get_registry().get("fstpu_http_request_seconds")
        routes = {values[0] for values, _ in hist.children()}
        assert {"/healthz", "/fleet",
                "/debug/traces/<id>"} <= routes
    finally:
        server.shutdown()
        server.server_close()


# ---- engine tier: tracing adds no traced work ---------------------------

@pytest.fixture(scope="module")
def tiny():
    cfg = LlamaConfig(vocab_size=97, hidden_size=32,
                      intermediate_size=64, num_hidden_layers=2,
                      num_attention_heads=4,
                      max_position_embeddings=64, dtype="float32")
    model = LlamaForCausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 4), jnp.int32))["params"]
    return model, params


def _ref(model, params, prompt, max_new):
    out = np.asarray(generate(model, params, jnp.asarray(prompt)[None],
                              max_new_tokens=max_new))
    return out[0, len(prompt):].tolist()


def test_engine_tracing_parity_one_compile(tiny):
    """Trace ids through submit are host-side bookkeeping only: greedy
    output stays token-identical to sequential generate with exactly
    ONE decode compile, and every timeline + debug-ring entry carries
    trace_id/parent_span_id."""
    model, params = tiny
    rng = np.random.RandomState(0)
    prompts = [rng.randint(3, 96, n).astype(np.int32)
               for n in (5, 11, 16, 7)]
    refs = [_ref(model, params, p, 8) for p in prompts]
    eng = ContinuousBatchingEngine(
        model, params, EngineConfig(num_slots=2, buckets=(8, 16),
                                    max_new_tokens=8, max_queue=16),
        wall=lambda: 4321.5)
    if not hasattr(eng._decode_jit, "_cache_size"):
        pytest.skip("jit cache introspection unavailable")
    reqs = [eng.submit(p, trace_id=f"{i:032x}",
                       parent_span_id=f"{i:016x}")
            for i, p in enumerate(prompts, start=1)]
    eng.run_until_idle()
    for i, (req, ref) in enumerate(zip(reqs, refs), start=1):
        assert req.tokens == ref
        d = eng.debug_request(req.request_id)
        assert d["trace_id"] == f"{i:032x}"
        assert d["parent_span_id"] == f"{i:016x}"
        # the engine's injectable wall clock anchors the timeline —
        # the replica half of the assembler's skew math is testable
        assert d["epoch_unix_s"] == 4321.5
        ph = d["phases"]
        assert abs(ph["queue_wait_s"] + ph["prefill_s"] +
                   ph["decode_s"] - ph["total_s"]) <= 1e-3
    assert eng._decode_jit._cache_size() == 1
    # the list endpoint's summaries carry the id too
    recent = eng.debug_requests()["recent"]
    assert {r["trace_id"] for r in recent} == \
        {f"{i:032x}" for i in range(1, 5)}
    # 413-class rejections keep their trace correlation as well
    from fengshen_tpu.serving import PromptTooLong
    with pytest.raises(PromptTooLong):
        eng.submit(rng.randint(3, 96, 40).astype(np.int32),
                   request_id="rej-1", trace_id="e" * 32,
                   parent_span_id="f" * 16)
    assert eng.debug_request("rej-1")["trace_id"] == "e" * 32


# ---- integration: real replicas, fault plan, assembled trace ------------

class _IntTok:
    eos_token_id = None
    pad_token_id = 0

    def encode(self, text):
        return [int(t) for t in text.split()]

    def decode(self, ids):
        return " ".join(str(int(t)) for t in ids)


def _start_replica(tiny, max_new=5, num_slots=2):
    from fengshen_tpu.api.main import (PipelineConfig, ServerConfig,
                                       build_stdlib_server)
    from fengshen_tpu.pipelines.text_generation import Pipeline
    model, params = tiny
    pipe = Pipeline(module=model, params=params, tokenizer=_IntTok(),
                    max_new_tokens=max_new, eos_token_id=None,
                    pad_token_id=0)
    engine = ContinuousBatchingEngine(
        model, params,
        EngineConfig(num_slots=num_slots, buckets=(8,),
                     max_new_tokens=max_new, max_queue=32,
                     pad_token_id=0))
    engine.warmup()
    engine.start()
    ready = threading.Event()
    ready.set()
    server = build_stdlib_server(
        ServerConfig(host="127.0.0.1", port=0, engine="continuous"),
        PipelineConfig(task="text_generation"), pipeline=pipe,
        engine=engine, ready=ready)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    return server, engine, thread


def _shutdown(fleet):
    for server, engine, _thread in fleet:
        server.shutdown()
        server.server_close()
        engine.stop()


def test_fleet_fault_yields_one_assembled_trace(tiny):
    """THE acceptance pin (ISSUE 11): a FleetFaultPlan fault at a
    chosen request index yields ONE assembled trace — the router span
    ledger shows attempt 1 (failed, faulted replica) + attempt 2 (ok,
    surviving replica) as children of the same trace_id, both
    replicas' per-process waterfalls attach (the wedged replica really
    executed its copy), phases sum exactly per process, traceview
    emits valid Chrome trace-event JSON, and every greedy answer is
    token-identical with tracing on."""
    model, params = tiny
    fleet = [_start_replica(tiny) for _ in range(2)]
    targets = [f"127.0.0.1:{s.server_address[1]}"
               for s, *_ in fleet]
    # wedge (not kill) the faulted attempt: the request is DELIVERED
    # and executed, its response lost — so the faulted replica has a
    # real per-process waterfall for the assembler to attach
    plan = FleetFaultPlan(wedge_at={2: targets[0]})
    transport = plan.wrap(UrllibTransport())
    router = FleetRouter(
        FleetConfig(replicas=targets, max_retries=2,
                    breaker_threshold=1, recovery_probes=1,
                    backoff_base_s=0.0, request_timeout_s=60.0),
        transport=transport, sleep=lambda s: None)
    transport.bind(router)
    try:
        router.poll_once()
        assert router.healthy_count() == 2
        rng = np.random.RandomState(1)
        prompts = [rng.randint(3, 96, n).astype(np.int32)
                   for n in (3, 5, 7, 4)]
        responses = []
        for p in prompts:
            code, body = router.route_generate(
                {"input_text": " ".join(str(t) for t in p)})
            responses.append((code, body))
        assert [c for c, _ in responses] == [200] * len(prompts)
        refs = [" ".join(str(t) for t in _ref(model, params, p, 5))
                for p in prompts]
        assert [b["result"] for _, b in responses] == refs
        assert plan.fired == [("wedge", 2, targets[0])]
        assert router.retries_total() == {"timeout": 1}

        # ONE trace tells the wedged request's whole story
        wedged_code, wedged = responses[2]
        tid = wedged["trace_id"]
        assert len({b["trace_id"] for _, b in responses}) == \
            len(prompts)                    # one trace per request
        trace = router.tracer.get_trace(tid)
        root = trace["spans"][0]
        attempts = [s for s in trace["spans"]
                    if s["name"] == "router/attempt"]
        assert len(attempts) == 2
        assert all(s["parent_span_id"] == root["span_id"]
                   for s in attempts)
        assert attempts[0]["attrs"]["replica"] == targets[0]
        assert attempts[0]["attrs"]["outcome"] == "timeout"
        assert attempts[1]["attrs"]["replica"] == targets[1]
        assert attempts[1]["attrs"]["outcome"] == "ok"

        # unwedge (process "restarted") so assembly can fetch the
        # faulted replica's waterfall; the fired coordinate stays
        # consumed — no re-fire
        plan.revive(targets[0])
        assembled = router.assemble(tid)
        assert assembled["request_id"] == wedged["request_id"]
        assert sorted(assembled["replicas"]) == sorted(targets)
        for name in targets:
            entry = assembled["replicas"][name]
            wf = entry["waterfall"]
            assert wf["request_id"] == wedged["request_id"]
            assert wf["state"] == "finished"
            # the per-process PR-8 invariant survives assembly:
            # phases sum exactly per process
            ph = wf["phases"]
            assert abs(ph["queue_wait_s"] + ph["prefill_s"] +
                       ph["decode_s"] - ph["total_s"]) <= 1e-3
            assert "offset_in_trace_s" in entry
            assert "clock_skew_s" in entry
            # both executions parent into THIS trace via their
            # attempt spans
            att_ids = {s["span_id"] for s in attempts}
            assert wf["trace_id"] == tid
            assert wf["parent_span_id"] in att_ids
        # both executions returned the same greedy tokens (the
        # idempotent surface, now visible end to end)
        a_wf = assembled["replicas"][targets[0]]["waterfall"]
        b_wf = assembled["replicas"][targets[1]]["waterfall"]
        assert a_wf["generated_tokens"] == b_wf["generated_tokens"]

        doc = chrome_trace(assembled)
        _validate_chrome(doc)
        procs = {e["args"]["name"] for e in doc["traceEvents"]
                 if e["ph"] == "M"}
        assert procs == {"router"} | set(targets)
        json.dumps(assembled, sort_keys=True)    # JSON-clean
    finally:
        _shutdown(fleet)


def test_fleet_kill_trace_records_failed_attempt(tiny):
    """A KILL (connect refused — the request provably never reached
    the replica): the trace still tells the story — attempt 1 failed
    on the dead replica, attempt 2 ok on the survivor — and assembly
    degrades the dead replica to an error entry instead of failing."""
    model, params = tiny
    fleet = [_start_replica(tiny) for _ in range(2)]
    targets = [f"127.0.0.1:{s.server_address[1]}"
               for s, *_ in fleet]
    plan = FleetFaultPlan(kill_at={1: targets[0]})
    transport = plan.wrap(UrllibTransport())
    router = FleetRouter(
        FleetConfig(replicas=targets, max_retries=2,
                    breaker_threshold=1, recovery_probes=1,
                    backoff_base_s=0.0, request_timeout_s=60.0),
        transport=transport, sleep=lambda s: None)
    transport.bind(router)
    try:
        router.poll_once()
        prompts = [np.asarray([5, 7, 9], np.int32),
                   np.asarray([4, 6], np.int32)]
        bodies = []
        for p in prompts:
            code, body = router.route_generate(
                {"input_text": " ".join(str(t) for t in p)})
            assert code == 200
            bodies.append(body)
        assert plan.fired == [("kill", 1, targets[0])]
        tid = bodies[1]["trace_id"]
        trace = router.tracer.get_trace(tid)
        attempts = [s for s in trace["spans"]
                    if s["name"] == "router/attempt"]
        assert [s["attrs"]["outcome"] for s in attempts] == \
            ["connect", "ok"]
        assembled = router.assemble(tid)
        dead = assembled["replicas"][targets[0]]
        assert dead["error"].startswith("unreachable")
        alive = assembled["replicas"][targets[1]]
        assert alive["waterfall"]["request_id"] == \
            bodies[1]["request_id"]
        _validate_chrome(chrome_trace(assembled))
    finally:
        _shutdown(fleet)
