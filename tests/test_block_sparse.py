"""Block-sparse Pallas attention vs dense-with-mask (interpret mode)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fengshen_tpu.ops import (bigbird_mask, longformer_mask,
                              make_attention_bias, dot_product_attention)
from fengshen_tpu.ops.pallas.block_sparse_attention import (
    block_sparse_attention)


def _qkv(seq):
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(1, seq, 2, 8), jnp.float32)
    k = jnp.asarray(rng.randn(1, seq, 2, 8), jnp.float32)
    v = jnp.asarray(rng.randn(1, seq, 2, 8), jnp.float32)
    return q, k, v


def _block_layout(mask, block):
    m = np.asarray(mask)
    n = m.shape[0] // block
    return m.reshape(n, block, n, block).any(axis=(1, 3))


@pytest.mark.parametrize("layout_fn", [
    lambda s, b: longformer_mask(s, b, num_window_blocks=3,
                                 global_block_indices=(0,)),
    lambda s, b: bigbird_mask(s, b, num_random_blocks=1,
                              num_global_blocks=1, num_window_blocks=3,
                              seed=1),
])
def test_block_sparse_matches_dense_masked(layout_fn):
    seq, block = 32, 8
    q, k, v = _qkv(seq)
    mask = layout_fn(seq, block)
    ref = dot_product_attention(q, k, v, mask=mask[None, None])
    layout = _block_layout(mask, block)
    # layouts from ops.masks are block-aligned, so blockified==original
    np.testing.assert_array_equal(
        np.kron(layout, np.ones((block, block), bool)), np.asarray(mask))
    out = block_sparse_attention(q, k, v, layout, block, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4)


def test_block_sparse_skips_absent_rows():
    seq, block = 16, 8
    q, k, v = _qkv(seq)
    layout = np.array([[True, False], [False, False]])
    out = block_sparse_attention(q, k, v, layout, block, interpret=True)
    # second q block has no present kv block → zeros
    np.testing.assert_allclose(np.asarray(out)[0, 8:], 0.0, atol=1e-6)
    # first q block attends only the first kv block
    ref = dot_product_attention(q[:, :8], k[:, :8], v[:, :8])
    np.testing.assert_allclose(np.asarray(out)[0, :8],
                               np.asarray(ref)[0], atol=1e-4)


def test_block_sparse_fused_backward_matches_dense(monkeypatch):
    """The fused layout-gated bwd kernels must match autodiff of
    dense-with-mask on rows that have at least one present block."""
    from fengshen_tpu.ops import longformer_block_layout
    seq, block = 32, 8
    q, k, v = _qkv(seq)
    layout = longformer_block_layout(seq, block, num_window_blocks=3,
                                     global_block_indices=(0,))
    mask = jnp.asarray(np.kron(layout, np.ones((block, block), bool)))

    def f_sparse(q, k, v):
        out = block_sparse_attention(q, k, v, layout, block, interpret=True)
        return (out ** 2).sum()

    def f_dense(q, k, v):
        out = dot_product_attention(q, k, v, mask=mask[None, None])
        return (out ** 2).sum()

    gs = jax.grad(f_sparse, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(f_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gs, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-3)


def test_sparse_impl_dispatches_to_pallas_kernel(monkeypatch):
    """impl='sparse' + sparse_layout must route to the Pallas kernel when
    eligible (VERDICT r1 weak #5: no more shelf-ware)."""
    from fengshen_tpu.ops import longformer_block_layout
    import fengshen_tpu.ops.pallas.block_sparse_attention as bsa
    import fengshen_tpu.ops.attention as attn_mod

    seq, block = 256, 128
    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(1, seq, 2, 128), jnp.float32)
    k = jnp.asarray(rng.randn(1, seq, 2, 128), jnp.float32)
    v = jnp.asarray(rng.randn(1, seq, 2, 128), jnp.float32)
    layout = longformer_block_layout(seq, block, num_window_blocks=1)

    calls = {}
    real = bsa.block_sparse_attention

    def spy(q, k, v, layout, blk, interpret=False):
        calls["hit"] = True
        return real(q, k, v, layout, blk, interpret=True)

    monkeypatch.setattr(bsa, "block_sparse_attention", spy)
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    out = dot_product_attention(q, k, v, impl="sparse",
                                sparse_layout=layout,
                                sparse_block_size=block)
    assert calls.get("hit"), "Pallas kernel was not dispatched"
    ref = dot_product_attention(
        q, k, v, impl="dense",
        mask=jnp.asarray(np.kron(layout, np.ones((block, block), bool))
                         )[None, None])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-3)


def test_sparse_impl_fallback_on_unaligned_shapes():
    """Non-tile-aligned shapes fall back to dense-with-expanded-mask."""
    from fengshen_tpu.ops import longformer_block_layout
    seq, block = 32, 8  # block not a multiple of 128 -> ineligible
    q, k, v = _qkv(seq)
    layout = longformer_block_layout(seq, block, num_window_blocks=3)
    out = dot_product_attention(q, k, v, impl="sparse",
                                sparse_layout=layout,
                                sparse_block_size=block)
    mask = jnp.asarray(np.kron(layout, np.ones((block, block), bool)))
    ref = dot_product_attention(q, k, v, mask=mask[None, None])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)
