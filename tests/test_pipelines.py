"""Pipeline / CLI / metrics / CRF tests."""

import argparse
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # full-fit/e2e lane: run with -m slow or no -m filter


def _bert_tokenizer(tmp_path):
    from transformers import BertTokenizer
    chars = list("今天天气很好坏非常糟糕开心难过测试句子北京上海人名地名")
    vocab = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"] + \
        sorted(set(chars))
    vf = tmp_path / "vocab.txt"
    vf.write_text("\n".join(vocab))
    return BertTokenizer(str(vf))


# -- metrics --------------------------------------------------------------

def test_metrics_mlm_acc():
    from fengshen_tpu.metrics import metrics_mlm_acc
    logits = np.zeros((1, 3, 4))
    logits[0, 0, 1] = 9
    logits[0, 1, 2] = 9
    logits[0, 2, 3] = 9
    labels = np.array([[1, 2, -100]])
    assert metrics_mlm_acc(logits, labels) == 1.0
    labels2 = np.array([[1, 0, -100]])
    assert metrics_mlm_acc(logits, labels2) == 0.5


def test_seq_entity_score_bio():
    from fengshen_tpu.metrics import SeqEntityScore
    id2label = {0: "O", 1: "B-PER", 2: "I-PER", 3: "B-LOC"}
    score = SeqEntityScore(id2label, markup="bio")
    score.update([[1, 2, 0, 3]], [[1, 2, 0, 3]])
    overall, per_class = score.result()
    assert overall["f1"] == 1.0
    score.reset()
    score.update([[1, 2, 0, 3]], [[1, 2, 0, 0]])
    overall, _ = score.result()
    assert 0 < overall["f1"] < 1.0


def test_get_entities_bios():
    from fengshen_tpu.metrics import get_entities
    tags = ["B-PER", "I-PER", "O", "S-LOC"]
    ents = get_entities(tags, markup="bios")
    assert ["PER", 0, 1] in ents and ["LOC", 3, 3] in ents


def test_bert_extract_item():
    from fengshen_tpu.metrics import bert_extract_item
    start = np.zeros((6, 3))
    end = np.zeros((6, 3))
    start[2, 1] = 9  # inner position 1 (after [CLS] strip)
    end[3, 1] = 9
    spans = bert_extract_item(start, end)
    assert spans == [(1, 1, 2)]


# -- CRF ------------------------------------------------------------------

def test_crf_loglik_and_decode():
    from fengshen_tpu.models.tagging import CRF
    crf = CRF(num_tags=4)
    rng = jax.random.PRNGKey(0)
    emissions = jnp.asarray(np.random.RandomState(0).randn(2, 6, 4),
                            jnp.float32)
    tags = jnp.asarray(np.random.RandomState(1).randint(0, 4, (2, 6)))
    mask = jnp.asarray([[1, 1, 1, 1, 1, 0], [1, 1, 1, 0, 0, 0]], jnp.int32)
    params = crf.init(rng, emissions, tags, mask)
    nll = crf.apply(params, emissions, tags, mask)
    assert np.isfinite(float(nll)) and float(nll) > 0

    decoded = crf.apply(params, emissions, mask, method=CRF.decode)
    assert decoded.shape == (2, 6)
    # brute-force check best path for the first (length-5) sequence
    import itertools
    p = params["params"]
    best_score, best_path = -1e30, None
    em = np.asarray(emissions)[0]
    for path in itertools.product(range(4), repeat=5):
        s = float(p["start_transitions"][path[0]]) + em[0, path[0]]
        for t in range(1, 5):
            s += float(p["transitions"][path[t - 1], path[t]]) + \
                em[t, path[t]]
        s += float(p["end_transitions"][path[4]])
        if s > best_score:
            best_score, best_path = s, path
    np.testing.assert_array_equal(np.asarray(decoded)[0][:5], best_path)


def test_crf_normalizer_brute_force():
    from fengshen_tpu.models.tagging import CRF
    import itertools
    crf = CRF(num_tags=3)
    emissions = jnp.asarray(np.random.RandomState(2).randn(1, 4, 3),
                            jnp.float32)
    tags = jnp.zeros((1, 4), jnp.int32)
    params = crf.init(jax.random.PRNGKey(0), emissions, tags)
    p = params["params"]
    em = np.asarray(emissions)[0]
    scores = []
    for path in itertools.product(range(3), repeat=4):
        s = float(p["start_transitions"][path[0]]) + em[0, path[0]]
        for t in range(1, 4):
            s += float(p["transitions"][path[t - 1], path[t]]) + \
                em[t, path[t]]
        s += float(p["end_transitions"][path[3]])
        scores.append(s)
    from scipy.special import logsumexp
    ref_z = logsumexp(scores)
    # nll of the all-zeros path
    s0 = float(p["start_transitions"][0]) + em[0, 0] + sum(
        float(p["transitions"][0, 0]) + em[t, 0] for t in range(1, 4)) + \
        float(p["end_transitions"][0])
    ref_nll = -(s0 - ref_z)
    nll = crf.apply(params, emissions, tags)
    np.testing.assert_allclose(float(nll), ref_nll, atol=1e-4)


# -- pipelines ------------------------------------------------------------

def test_text_classification_pipeline_train_and_predict(tmp_path, mesh8):
    from fengshen_tpu.pipelines.text_classification import (
        TextClassificationPipeline)
    from fengshen_tpu.models.megatron_bert import MegatronBertConfig

    tok = _bert_tokenizer(tmp_path)
    parser = argparse.ArgumentParser()
    parser = TextClassificationPipeline.add_pipeline_specific_args(parser)
    args = parser.parse_args([
        "--max_length", "16", "--train_batchsize", "4", "--max_steps", "2",
        "--log_every_n_steps", "1", "--warmup_steps", "1",
        "--default_root_dir", str(tmp_path / "runs"),
        "--save_ckpt_path", str(tmp_path / "ckpt"),
        "--load_ckpt_path", str(tmp_path / "none")])

    cfg = MegatronBertConfig(
        vocab_size=len(tok), hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=32, dtype="float32", num_labels=2)
    pipe = TextClassificationPipeline(args=args, tokenizer=tok, config=cfg)

    data = [{"sentence": "今天天气很好", "label": 1},
            {"sentence": "非常糟糕难过", "label": 0}] * 8

    class DS:
        def __len__(self):
            return len(data)

        def __getitem__(self, i):
            return data[i]

    pipe.train({"train": DS()})
    result = pipe("今天天气很好")
    assert set(result) == {"label", "score"}
    results = pipe(["今天天气很好", "非常糟糕"])
    assert len(results) == 2


def test_sequence_tagging_pipeline_predict(tmp_path):
    from fengshen_tpu.pipelines.sequence_tagging import (
        SequenceTaggingPipeline)
    from fengshen_tpu.models.megatron_bert import MegatronBertConfig
    tok = _bert_tokenizer(tmp_path)
    cfg = MegatronBertConfig(
        vocab_size=len(tok), hidden_size=32, num_hidden_layers=1,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=32, dtype="float32")
    pipe = SequenceTaggingPipeline(
        args=None, tokenizer=tok, config=cfg,
        labels=["O", "B-LOC", "I-LOC"])
    out = pipe("北京上海")
    assert isinstance(out, list)
    for ent in out:
        assert set(ent) == {"entity", "type", "start", "end"}


# -- CLI ------------------------------------------------------------------

def test_cli_usage_and_unknown_task(capsys):
    from fengshen_tpu.cli.fengshen_pipeline import main
    assert main([]) == 2
    assert main(["text_classification", "explode"]) == 2
    with pytest.raises(SystemExit, match="unknown task"):
        main(["not_a_task", "predict"])


# -- API ------------------------------------------------------------------

def test_api_build_app(tmp_path):
    fastapi = pytest.importorskip("fastapi")
    from fastapi.testclient import TestClient
    from fengshen_tpu.api.main import build_app, load_config

    cfg = tmp_path / "cfg.json"
    cfg.write_text(json.dumps({
        "SERVER": {"port": 8123},
        "PIPELINE": {"task": "text_classification"}}))
    server_cfg, pipeline_cfg = load_config(str(cfg))
    assert server_cfg.port == 8123

    class FakePipeline:
        def __call__(self, text):
            return {"label": 1, "score": 0.9}

    app = build_app(pipeline_cfg, pipeline=FakePipeline())
    client = TestClient(app)
    r = client.post("/api/text_classification",
                    json={"input_text": "你好"})
    assert r.status_code == 200
    assert r.json()["result"]["label"] == 1
    assert client.get("/healthz").json()["status"] == "ok"


def test_api_stdlib_server_roundtrip():
    """The dependency-free REST fallback serves the same surface as the
    FastAPI app: POST /api/<task> + GET /healthz (fastapi is not in
    this image, so this path IS the runnable serving surface here)."""
    import json as json_mod
    import threading
    import urllib.request

    from fengshen_tpu.api.main import (PipelineConfig, ServerConfig,
                                       build_stdlib_server)

    calls = []

    def fake_pipeline(text):
        calls.append(text)
        return [{"label": "1", "score": 0.9}]

    server = build_stdlib_server(
        ServerConfig(host="127.0.0.1", port=0),
        PipelineConfig(task="text_classification"),
        pipeline=fake_pipeline)
    port = server.server_address[1]
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=10) as r:
            health = json_mod.loads(r.read())
        assert health == {"status": "ok", "task": "text_classification"}

        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/api/text_classification",
            data=json_mod.dumps({"input_text": "天气很好"}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as r:
            out = json_mod.loads(r.read())
        assert out["result"][0]["label"] == "1"
        assert calls == ["天气很好"]

        # missing field → 422, wrong path → 404
        bad = urllib.request.Request(
            f"http://127.0.0.1:{port}/api/text_classification",
            data=b"{}", headers={"Content-Type": "application/json"})
        try:
            urllib.request.urlopen(bad, timeout=10)
            assert False, "expected 422"
        except urllib.error.HTTPError as e:
            assert e.code == 422
    finally:
        server.shutdown()
