"""known-bad fixture: PartitionSpec axis typos (silently replicate)."""

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

RULES = [
    ("embed", P("tenosr", "fsdp")),       # typo'd tensor axis
    ("mlp", P(("data", "fsp"), None)),    # typo'd fsdp inside a tuple
]


def shard(mesh, x):
    spec = jax.sharding.PartitionSpec("batch", None)  # not a mesh axis
    return jax.device_put(x, NamedSharding(mesh, spec))


# declarative sharding tables (docs/sharding.md), both malformed
BAD_PARAM_LOGICAL_AXES = [
    ("q_proj/kernel", ("embed", "head")),   # typo'd logical axis
    ("norm", ("nrom",)),                    # typo'd logical axis
]

BAD_LOGICAL_AXIS_RULES = (
    ("heads", "tenosr"),                    # typo'd mesh axis
    ("mpl", "tensor"),                      # typo'd logical axis
)
