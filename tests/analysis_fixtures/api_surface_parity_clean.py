"""Clean fixture for `api-surface-parity`.

Both surfaces expose the same `(METHOD, path)` set, including a
parameterised fastapi route matched by a `startswith` prefix dispatch
on the stdlib side (both normalise to `/requests/*`).
"""

from http.server import BaseHTTPRequestHandler

from fastapi import FastAPI

app = FastAPI()


@app.get("/healthz")
def healthz():
    return {"ok": True}


@app.post("/infer")
def infer(payload: dict):
    return {"text": ""}


@app.get("/requests/{request_id}")
def request_status(request_id: str):
    return {"id": request_id}


class Handler(BaseHTTPRequestHandler):
    def do_GET(self):
        if self.path == "/healthz":
            self.send_response(200)
        elif self.path.startswith("/requests/"):
            self.send_response(200)
        else:
            self.send_response(404)

    def do_POST(self):
        if self.path == "/infer":
            self.send_response(200)
        else:
            self.send_response(404)
