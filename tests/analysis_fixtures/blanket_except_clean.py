"""known-clean fixture: specific handlers + justified blankets.

The string below must NOT trip the rule (it did trip the old regex
lint — that's the false-positive class the AST port removes):

    except Exception:
"""

HELP = "wrap risky calls in try/...: except Exception: handle it"


def load(path):
    try:
        return open(path).read()
    except OSError:
        return None


def probe(fn):
    try:
        fn()
    except Exception:  # noqa: BLE001 - re-raised below after cleanup
        raise


def best_effort(fn):
    try:
        fn()
    except Exception:  # pragma: no cover - defensive probe
        pass
