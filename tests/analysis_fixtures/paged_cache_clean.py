"""known-clean fixture: the paged KV-cache idiom (docs/serving.md) —
free-list math lives on the HOST scheduler thread, the traced decode
is a pure gather/scatter program, and every metric bump / host sync
happens between jit boundaries.

Mirrors `fengshen_tpu/serving/paged_cache.py` + the engine's paged
decode tick: `metrics-in-traced-code`, `blocking-transfer` and
`host-divergence` must all stay silent here — if one fires, the
analyzer would also flag the real serving modules and block the merge
gate.
"""

import jax
import jax.numpy as jnp
import numpy as np

from fengshen_tpu.observability import get_registry, span

REG = get_registry()
TICKS = REG.counter("fx_paged_decode_ticks_total", "ticks")
DEFERRED = REG.counter("fx_paged_deferred_total", "deferred admissions")


class FreeList:
    """Host-side block allocator: plain Python lists, never traced.
    Block 0 is the reserved null block free lanes park on."""

    def __init__(self, num_blocks):
        self._free = list(range(num_blocks - 1, 0, -1))

    def alloc(self, n):
        if n > len(self._free):
            return None
        return [self._free.pop() for _ in range(n)]

    def free(self, blocks):
        self._free.extend(blocks)


@jax.jit
def paged_decode(pool, table, index, tokens, active):
    """The traced program: pure array math. Writes each lane's token
    K/V at `table[lane, idx // bs] * bs + idx % bs`, gathers the
    lane's blocks back into a contiguous view — no metrics, no host
    pulls, no ambient randomness."""
    num_blocks, block_size, width = pool.shape
    table = jnp.where(active[:, None], table, 0)   # park free lanes
    blk = jnp.take_along_axis(table, (index // block_size)[:, None],
                              axis=-1)[:, 0]
    pos = blk * block_size + index % block_size
    flat = pool.reshape(num_blocks * block_size, width)
    flat = flat.at[pos].set(tokens[:, None].astype(flat.dtype))
    gather = ((table * block_size)[:, :, None] +
              jnp.arange(block_size)[None, None, :]).reshape(
                  table.shape[0], -1)
    lanes = jnp.take(flat, gather, axis=0)
    return flat.reshape(pool.shape), index + 1, lanes.sum(-1)


def tick(state, freelist, queued):
    """One scheduler tick: admission math and metric bumps on the
    host, ONE jitted decode, host sync after dispatch."""
    pool, table, index, tokens, active = state
    for need in queued:
        blocks = freelist.alloc(need)
        if blocks is None:
            DEFERRED.inc()
            break
    with span("serving/decode"):
        pool, index, scores = paged_decode(pool, table, index, tokens,
                                           active)
        out = np.asarray(scores)           # host sync AFTER dispatch
    TICKS.inc()
    return (pool, table, index, tokens, active), out
