"""known-clean fixture: host values read OUTSIDE traces, passed in."""

import os
import random
import time

import jax


def make_run_config():
    # host-side setup: reading the environment here is idiomatic
    return {
        "seed": int(os.environ.get("SEED", "0")),
        "started": time.time(),
        # seeded => identical on every host
        "jitter": random.Random(17).random(),
    }


def build_step(cfg):
    @jax.jit
    def step(x, rng):
        # randomness comes in through the functional PRNG, not the host
        return x + jax.random.normal(rng, x.shape) * cfg["jitter"]

    return step
