"""Known-bad fixture for `metric-contract`.

One family, two schemas: the relay path registers
`fstpu_fixture_requests_total` with an extra `shard` label, which the
registry rejects at runtime — but only on the relay code path.
"""

from fengshen_tpu.observability import registry


def serve_metrics(r):
    return r.counter("fstpu_fixture_requests_total",
                     "requests seen", labelnames=("route",))


def relay_metrics(r):
    return r.counter("fstpu_fixture_requests_total",
                     "requests seen", labelnames=("route", "shard"))


def default_metrics():
    return serve_metrics(registry.get_registry())
