"""Clean fixture for `blocking-under-lock`: the snapshot-then-block
idiom, and the Condition.wait exemption (waiting RELEASES the held
condition — that is what condition variables are for)."""

import threading
import urllib.request


class Router:
    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition()
        self._replicas = []

    def rebalance(self):
        with self._lock:
            targets = list(self._replicas)  # snapshot under the lock
        for url in targets:                 # slow work outside it
            _fetch_health(url)

    def wait_for_work(self):
        with self._cv:
            # waiting the condition you hold releases it: not a stall
            self._cv.wait(timeout=1.0)

    def note(self, url):
        with self._lock:
            self._replicas.append(url)      # cheap host work only


def _fetch_health(url):
    return urllib.request.urlopen(url)
