"""known-clean fixture: the kernel dispatch seam idiom
(docs/kernels.md) — the capability probe runs ONCE on the host and is
cached, the pallas-vs-xla decision is a plain Python bool resolved at
trace time (never a traced value, never re-probed inside jit), the
dispatch gauge and the loud `kernel_dispatch` line land at import/
startup between jit boundaries, and the traced kernel bodies are pure
array programs.

Mirrors `fengshen_tpu/ops/pallas/__init__.py` + the decode/CE seams:
`metrics-in-traced-code`, `blocking-transfer` and `host-divergence`
must all stay silent here — if one fires, the analyzer would also
flag the real kernel layer and block the merge gate. The classic
hazard this shape avoids: calling the probe (an env + backend lookup)
from INSIDE a traced function, which would make the compiled program
depend on ambient host state and re-trace per call.
"""

import os

import jax
import jax.numpy as jnp

from fengshen_tpu.observability import get_registry

REG = get_registry()
DISPATCH = REG.gauge("fx_kernel_dispatch", "chosen kernel impl",
                     labelnames=("op", "impl"))

_PROBE_CACHE = {}


def probe():
    """Host-side capability probe, cached by (backend, force env):
    runs outside every trace, so the dispatch decision below is a
    compile-time constant of the program."""
    key = (jax.default_backend(), os.environ.get("FX_KERNEL_FORCE"))
    if key not in _PROBE_CACHE:
        forced = key[1]
        _PROBE_CACHE[key] = (forced == "pallas") or (
            forced != "xla" and key[0] == "tpu")
    return _PROBE_CACHE[key]


def _xla_softmax_attn(q, k, v):
    """The stock lowering: pure array math, fp32 softmax stats."""
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32)
    probs = jax.nn.softmax(scores / q.shape[-1] ** 0.5, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)


def _blocked_attn(q, k, v):
    """Stand-in for the Mosaic kernel: same contract, online softmax
    over k blocks — still a pure traced program, no host pulls."""
    blk = 128
    n = k.shape[1] // blk

    def step(carry, i):
        acc, m, l = carry
        kb = jax.lax.dynamic_slice_in_dim(k, i * blk, blk, axis=1)
        vb = jax.lax.dynamic_slice_in_dim(v, i * blk, blk, axis=1)
        s = jnp.einsum("bqhd,bkhd->bhqk", q, kb,
                       preferred_element_type=jnp.float32)
        new_m = jnp.maximum(m, s.max(-1))
        p = jnp.exp(s - new_m[..., None])
        corr = jnp.exp(m - new_m)
        l = l * corr + p.sum(-1)
        o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(vb.dtype), vb)
        acc = acc * corr.transpose(0, 2, 1)[..., None] + o
        return (acc, new_m, l), None

    acc0 = jnp.zeros(q.shape, jnp.float32)
    m0 = jnp.full((q.shape[0], q.shape[2], q.shape[1]), -1e30,
                  jnp.float32)
    l0 = jnp.zeros((q.shape[0], q.shape[2], q.shape[1]), jnp.float32)
    (acc, _, l), _ = jax.lax.scan(step, (acc0, m0, l0), jnp.arange(n))
    return (acc / l.transpose(0, 2, 1)[..., None]).astype(q.dtype)


# the decision is taken ONCE, on the host, while building the program —
# the jitted fn closes over a concrete Python callable
_IMPL = _blocked_attn if probe() else _xla_softmax_attn
DISPATCH.labels("attention", "pallas" if probe() else "xla").set(1.0)


@jax.jit
def attention(q, k, v):
    """The traced entry point: by the time tracing starts the impl is
    already a fixed callable; nothing in here reads env, backend, or
    metrics state."""
    return _IMPL(q, k, v)


def startup_report(log=None):
    """Loud dispatch line at startup (host-side, between jits):
    structured event when a sink exists, stderr otherwise."""
    info = {"event": "kernel_dispatch",
            "attention": "pallas" if probe() else "xla"}
    if log is not None:
        log(info)
    return info
