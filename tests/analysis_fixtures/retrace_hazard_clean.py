"""known-clean fixture: arrays passed in, statics declared."""

import functools

import jax
import jax.numpy as jnp

POS_TABLE = jnp.arange(2048)


@jax.jit
def embed(x, pos_table):  # the table is a traced operand
    return x + pos_table[: x.shape[-1]]


def call_embed(x):
    # host-side call: closing over the module array here is fine
    return embed(x, POS_TABLE)


@functools.partial(jax.jit, static_argnums=(1,))
def pad(x, widths=(1, 1)):  # hashable default + declared static
    return jnp.pad(x, list(widths))


@jax.jit
def shift(x, offset=None):  # None default resolved in-body
    if offset is None:
        offset = jnp.zeros(())
    return x + offset
