"""known-clean fixture: the distributed-tracing idiom (ISSUE 11,
docs/observability.md "Distributed tracing") — ALL trace bookkeeping
lives on the host, on the router/scheduler threads, between jit
boundaries. Trace ids come from a (seedable) `random.Random`, span
starts from `time.monotonic()` with a `time.time()` wall anchor, and
the ledger appends plain dicts under a lock — which is only safe
because none of it ever enters a traced program: the decode tick the
spans DESCRIBE stays a pure device function. The tempting regressions
this fixture guards: minting a trace/span id inside traced code
(host-divergence: `random`/`uuid` under trace), stamping a span's wall
anchor inside a jitted step (host-divergence: `time.*` under trace),
pulling a device value per request to enrich span attrs
(blocking-transfer), or bumping the `fstpu_trace_*` counters from a
traced helper (metrics-in-traced-code).

Mirrors `fengshen_tpu/observability/tracectx.py`'s ledger around
`fengshen_tpu/fleet/router.py`'s attempt loop: if a rule fires here,
it would also flag the real modules and block the merge gate.
"""

import random
import time

import jax
import jax.numpy as jnp
import numpy as np

from fengshen_tpu.observability import get_registry

REG = get_registry()
TRACES = REG.counter("fx_trace_started_total", "traces minted")
ATTEMPTS = REG.histogram("fx_fleet_attempt_seconds",
                         "attempt seconds by outcome",
                         labelnames=("outcome",))


@jax.jit
def traced_decode_tick(cache, tokens, phys, active):
    """The work a span DESCRIBES: pure gathers/scatters — no clock,
    no rng-for-ids, no counter mutation ever lands in here."""
    n = tokens.shape[0]
    cache = cache.at[jnp.arange(n), phys].set(tokens)
    nxt = jnp.where(active, tokens + 1, 0).astype(jnp.int32)
    return cache, nxt


def mint_ids(rng=random.Random(0)):
    """Host-side id mint (the seedable test form): W3C-shaped hex ids
    drawn OUTSIDE every traced program."""
    trace_id = f"{rng.getrandbits(128) or 1:032x}"
    span_id = f"{rng.getrandbits(64) or 1:016x}"
    return trace_id, span_id


def record_attempt(ledger, trace_id, replica, send,
                   clock=time.monotonic, wall=time.time):
    """The router's attempt span: start/end stamps from the HOST
    monotonic clock, the wall anchor taken once at span start, the
    outcome histogram bumped after the HTTP round-trip returns —
    none of it inside a jit boundary."""
    _, span_id = mint_ids()
    span = {"span_id": span_id, "replica": replica,
            "epoch_unix_s": round(wall(), 6), "t0": clock()}
    ok = send(replica)
    span["duration_s"] = round(clock() - span["t0"], 6)
    span["outcome"] = "ok" if ok else "connect"
    ATTEMPTS.labels(span["outcome"]).observe(span["duration_s"])
    ledger.setdefault(trace_id, []).append(span)
    return span


def drive_traced_request(state, tokens, ledger):
    """One traced tick bracketed by host-side spans: the jit boundary
    is crossed exactly once, and the host sync (np.array) happens
    strictly AFTER it — the span end stamp reads the host clock, not a
    device value."""
    trace_id, _ = mint_ids()
    TRACES.inc()
    cache, phys, active = state
    t0 = time.monotonic()
    cache, nxt = traced_decode_tick(cache, tokens, phys, active)
    out = np.array(nxt)            # host sync OUTSIDE the jit
    ledger.setdefault(trace_id, []).append(
        {"name": "decode", "duration_s": time.monotonic() - t0})
    return cache, out
