"""known-bad fixture: hash-ordered iteration feeding SPMD state."""

import jax
import jax.numpy as jnp


def gather_stats(params, skip):
    stats = {}
    for name in set(params) - set(skip):  # PYTHONHASHSEED order
        stats[name] = jax.lax.psum(params[name], "data")
    return stats


def stack_overlap(a, b):
    out = []
    for key in a.keys() & b.keys():  # set algebra over keys
        out.append(jnp.stack([a[key], b[key]]))
    return out
