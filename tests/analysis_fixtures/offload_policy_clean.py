"""known-clean fixture: the memory-placement idiom (docs/offload.md) —
the capability probe, the offload-policy resolution, and the placement
gauges are HOST code that runs strictly OUTSIDE traced programs,
between jit boundaries.

Mirrors `fengshen_tpu/trainer/memory.py` + the offloaded two-program
step: the probe's tiny transfer and `block_until_ready`, the byte-math
placement decision, and the gauge sets all happen around the jitted
grad/update programs, never inside one. None of `host-divergence`,
`blocking-transfer`, or `metrics-in-traced-code` may fire here — if one
does, the analyzer would also flag the real subsystem and block the
merge gate (or a rule lost precision).
"""

import jax
import jax.numpy as jnp
import numpy as np

from fengshen_tpu.observability import get_registry

REG = get_registry()
LEVEL = REG.gauge("fx_offload_level", "resolved ladder level")
SUPPORTED = REG.gauge("fx_memory_kind_supported", "probe bits",
                      labelnames=("kind",))


def probe_kind(kind):
    """The probe's shape: attempt a sharding construction plus a tiny
    transfer ON THE HOST — the block_until_ready is legal because no
    traced program is anywhere on the stack."""
    device = jax.devices()[0]
    try:
        sharding = jax.sharding.SingleDeviceSharding(device,
                                                     memory_kind=kind)
        x = jax.device_put(np.ones((8,), np.uint8), sharding)
        jax.block_until_ready(x)
        return True
    except ValueError:
        return False


def resolve_level(params_bytes, opt_bytes, budget):
    """Placement math: pure host integers, no arrays at all."""
    if budget is None or 2 * params_bytes + opt_bytes <= budget:
        return 0
    if 2 * params_bytes <= budget:
        return 1
    return 2


def grad_step(params, batch):
    # the traced program: pure array math — no probes, no gauges
    pred = batch["x"] @ params["w"]
    return jax.tree_util.tree_map(
        lambda w: w * pred.sum(), params)


def offloaded_fit(params, batches, host_sharding):
    """The offloaded-step choreography: jitted compute with explicit
    host parking BETWEEN the programs, gauges set once on the host."""
    supported = probe_kind("pinned_host")
    SUPPORTED.labels("pinned_host").set(1.0 if supported else 0.0)
    LEVEL.set(float(resolve_level(1 << 20, 2 << 20, None)))
    grad_jit = jax.jit(grad_step)
    update_jit = jax.jit(
        lambda p, g: jax.tree_util.tree_map(
            lambda a, b: a - 0.1 * b, p, g))
    moments = jax.device_put(
        jax.tree_util.tree_map(jnp.zeros_like, params), host_sharding)
    for batch in batches:
        grads = grad_jit(params, batch)
        # H2D / D2H between the two programs, outside any trace
        moments_dev = jax.device_put(moments)
        params = update_jit(params, grads)
        moments = jax.device_put(moments_dev, host_sharding)
    return params, moments
