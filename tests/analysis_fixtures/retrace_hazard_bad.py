"""known-bad fixture: jit cache/constant hazards."""

import functools

import jax
import jax.numpy as jnp

POS_TABLE = jnp.arange(2048)  # module-level device array


@jax.jit
def embed(x):
    return x + POS_TABLE[: x.shape[-1]]  # closure -> baked constant


@functools.partial(jax.jit)
def pad(x, widths=[1, 1]):  # unhashable default, no static_argnums
    return jnp.pad(x, widths)


@jax.jit
def scale(x, factors={}):  # unhashable default, no static_argnums
    return x * factors.get("gain", 1.0)
