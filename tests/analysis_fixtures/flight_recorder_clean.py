"""known-clean fixture: the request-timeline / flight-recorder idiom
(ISSUE 8, docs/observability.md "Request tracing" / "Flight recorder")
— the decode tick stays ONE pure traced program, while ALL lifecycle
bookkeeping (timestamped timeline events, the recorder's event ring,
metric snapshots, the post-mortem dump) happens on the scheduler
thread between jit boundaries. The timeline is a tempting place to
leak `time.monotonic()` into traced code (host-divergence), an
`.item()` per committed token (blocking-transfer), or a counter bump
inside the tick (metrics-in-traced-code) — none may happen.

Mirrors `fengshen_tpu/serving/engine.py`'s tick + timeline wiring and
`fengshen_tpu/observability/{timeline,flightrecorder}.py`: if a rule
fires here, it would also flag the real modules and block the merge
gate.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np

from fengshen_tpu.observability import (FlightRecorder, RequestTimeline,
                                        get_registry, span)

REG = get_registry()
COMMITTED = REG.counter("fx_timeline_committed_total",
                        "tokens committed by ticks")
PHASES = REG.histogram("fx_request_phase_seconds", "phase seconds",
                       labelnames=("phase",))


@jax.jit
def decode_tick(cache, history, tokens, phys, active, logits_table):
    """The traced tick: pure gathers/scatters over device state — no
    clocks, no host pulls, no metric mutation."""
    n = tokens.shape[0]
    history = history.at[jnp.arange(n), phys].set(tokens)
    step_logits = logits_table[tokens]
    nxt = jnp.argmax(step_logits, axis=-1).astype(jnp.int32)
    nxt = jnp.where(active, nxt, 0)
    cache = cache.at[jnp.arange(n), phys].set(nxt)
    return cache, history, nxt


def host_tick(state, timelines, logits_table, clock=time.monotonic):
    """Scheduler-side driver: the ONLY place clocks are read, device
    values cross to the host, timelines grow, and metrics move."""
    cache, history, tokens, phys, active = state
    t0 = clock()
    with span("fx/decode"):
        cache, history, nxt = decode_tick(cache, history, tokens, phys,
                                          active, logits_table)
        nxt = np.array(nxt)          # host sync AFTER the jit boundary
    dt = clock() - t0
    t_commit = clock()
    for i, tl in enumerate(timelines):
        if active[i]:
            tl.add(t_commit, "commit", n=1, tick_s=round(dt, 6))
    COMMITTED.inc(int(np.asarray(active).sum()))
    phys = np.asarray(phys) + np.asarray(active).astype(np.int32)
    return (cache, history, nxt, phys.astype(np.int32), active)


def finish_request(recorder: FlightRecorder, tl: RequestTimeline,
                   clock=time.monotonic) -> dict:
    """Terminal bookkeeping: derive the waterfall, observe the phase
    histogram, feed the recorder's ring — all host-side."""
    end = clock()
    tl.add(end, "finished", reason="length")
    phases = tl.phases(end)
    for key in ("queue_wait_s", "prefill_s", "decode_s"):
        PHASES.labels(key[:-2]).observe(phases[key])
    recorder.record({"event": "fx_finish", "phases": phases})
    return phases


def post_mortem(recorder: FlightRecorder, reason: str) -> str:
    """The dump trigger: ring + providers to disk, never traced."""
    recorder.snapshot_metrics([REG], force=True)
    return recorder.dump(reason=reason)
