"""Clean fixture for `donated-buffer-use`.

The donate-and-rebind idiom: the caller's name for the donated buffer
is reassigned to the result of the donating call, so the dead buffer
is unreachable afterwards — including across loop iterations.
"""

import jax


def _step_impl(state, batch):
    return state + batch


class Stepper:
    def __init__(self):
        self._step = jax.jit(_step_impl, donate_argnums=(0,))

    def run(self, state, batches):
        for batch in batches:
            state = self._step(state, batch)   # rebind: old buffer dead
        return state
