"""known-clean fixture: the KV-handoff idiom (ISSUE 13,
docs/disaggregation.md) — lane export/adopt is EAGER host-orchestrated
array work between jit boundaries, and the transfer plane is pure
stdlib. The exported prefix is gathered eagerly (no new jitted
programs: the engine's pinned compile counts must survive handoffs),
the payload is checksummed and base64-framed on the host, the push is
a blocking HTTP call on the coordinator thread (NEVER inside traced
code), and the `fstpu_disagg_*` counters mutate only around those
host steps. The tempting regressions this fixture guards: jitting the
gather/scatter of the lane (a new program per shape — compile-count
drift), hashing or pushing a payload inside a traced helper
(blocking-transfer), bumping the fallback counters in traced code
(metrics-in-traced-code), or branching traced code on a device value
of the lane cursor (host-divergence).

Mirrors `fengshen_tpu/serving/handoff.py`'s export/adopt around
`fengshen_tpu/disagg/transfer.py`'s seal/push: if a rule fires here,
it would also flag the real modules and block the merge gate.
"""

import base64
import hashlib
import json

import jax
import jax.numpy as jnp
import numpy as np

from fengshen_tpu.observability import get_registry

REG = get_registry()
FALLBACKS = REG.counter("fx_disagg_fallbacks_total",
                        "handoffs degraded to local decode",
                        labelnames=("reason",))
PAYLOAD_BYTES = REG.counter("fx_disagg_payload_bytes_total",
                            "encoded lane payload bytes")


@jax.jit
def decode_tick(cache, tokens, phys):
    """What both tiers run per tick: pure scatters — export/adopt
    never add hashing, HTTP, or counter mutation in here."""
    n = tokens.shape[0]
    cache = cache.at[jnp.arange(n), phys].set(tokens)
    return cache, (tokens + 1).astype(jnp.int32)


def export_lane(cache, slot, phys):
    """EAGER gather of the committed prefix: host-side jnp outside any
    jit (zero new compiled programs), then base64 framing + checksum —
    all plain host bytes work on the coordinator thread."""
    lane = np.asarray(jax.lax.slice_in_dim(
        jnp.take(cache, slot, axis=0), 0, phys, axis=0))
    body = {"shape": list(lane.shape), "dtype": str(lane.dtype),
            "data": base64.b64encode(lane.tobytes()).decode("ascii")}
    raw = json.dumps(body, sort_keys=True).encode()
    body["checksum"] = hashlib.sha256(raw).hexdigest()
    PAYLOAD_BYTES.inc(len(raw))
    return body


def adopt_lane(cache, payload, slot):
    """EAGER scatter of the wire lane into a free slot: the pool
    update is a host-orchestrated `.at[].set` outside every jit."""
    lane = jnp.asarray(np.frombuffer(
        base64.b64decode(payload["data"]),
        dtype=np.dtype(payload["dtype"])).reshape(payload["shape"]))
    return cache.at[slot, : lane.shape[0]].set(lane)


def push_with_fallback(payload, push, decode_locally):
    """The coordinator's prefill-side loop: the blocking push and the
    fallback counter live on the request thread, strictly between jit
    boundaries — a failed handoff is a counted local decode, never a
    client error."""
    try:
        push(payload)
        return "redirected"
    except OSError:
        FALLBACKS.labels("connect").inc()
        decode_locally()
        return "fallback"
