"""known-clean fixture: metrics recorded on the host, around the jit —
and jax's `.at[...].set(...)` (NOT a metric mutation) inside it."""

import jax
import jax.numpy as jnp

from fengshen_tpu.observability import get_registry

REG = get_registry()
STEPS = REG.counter("fx_clean_steps_total", "steps")
LOSS_HIST = REG.histogram("fx_clean_loss", "loss samples")


@jax.jit
def step(x):
    # functional-update idiom: receiver is a subscript, not a metric
    x = x.at[0].set(jnp.float32(0.0))
    return x * 2


def run_one(state, batch):
    # host side: dispatch the jitted step, then record what came back
    out = step(batch)
    STEPS.inc()
    LOSS_HIST.observe(float(out.mean()))
    REG.gauge("fx_clean_lr", "lr").set(0.1)
    return state, out
