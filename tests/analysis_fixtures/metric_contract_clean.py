"""Clean fixture for `metric-contract`.

Each family is registered exactly once with a single schema; reusing
the same get-or-create call from several sites with the SAME
(kind, labelnames) is fine.
"""

from fengshen_tpu.observability import registry


def tick_counter(r):
    return r.counter("fstpu_fixture_ticks_total",
                     "scheduler ticks", labelnames=("phase",))


def depth_gauge(r):
    return r.gauge("fstpu_fixture_queue_depth",
                   "queued requests", labelnames=("lane",))


def default_metrics():
    r = registry.get_registry()
    return tick_counter(r), depth_gauge(r)
