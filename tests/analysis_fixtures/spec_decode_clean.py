"""known-clean fixture: the speculative decode tick idiom (ISSUE 7,
docs/serving.md "Speculative decoding") — the n-gram drafter, verify
forward, and accept/commit math are ONE pure traced program over the
on-device history ring (the matcher is a tempting place to leak an
`.item()` or a host-side loop over lanes — it must not), while metric
bumps (drafted/accepted counters) and the per-lane commit bookkeeping
happen on the scheduler thread between jit boundaries.

Mirrors `fengshen_tpu/serving/engine.py`'s spec tick +
`fengshen_tpu/utils/generate.py`'s `_ngram_propose_lanes` /
`_spec_round_tokens`: `host-divergence`, `blocking-transfer` and
`metrics-in-traced-code` must all stay silent here — if one fires, the
analyzer would also flag the real modules and block the merge gate.
"""

import jax
import jax.numpy as jnp
import numpy as np

from fengshen_tpu.observability import get_registry, span

REG = get_registry()
DRAFTED = REG.counter("fx_spec_drafted_total", "drafted tokens")
ACCEPTED = REG.counter("fx_spec_accepted_total", "accepted tokens")


def _ngram_draft(history, t, gamma):
    """The traced drafter: match the 2-token suffix ending at each
    lane's own cursor and propose what followed the latest earlier
    occurrence — pure gathers, no host pull, no randomness."""
    def one(row, ti):
        width = row.shape[0]
        suffix = jax.lax.dynamic_slice_in_dim(row, ti - 2, 2)
        wins = jnp.stack([row[:width - 1], row[1:]], axis=-1)
        pos = jnp.arange(width - 1)
        match = jnp.all(wins == suffix[None, :], axis=-1) & \
            (pos + 2 < ti)
        j = jnp.max(jnp.where(match, pos, -1))
        idx = jnp.clip(j + 2 + jnp.arange(gamma), 0, width - 1)
        return jnp.where(j >= 0, row[idx], row[ti - 1])
    return jax.vmap(one)(history, t)


@jax.jit
def spec_tick(history, tokens, phys, active, logits_table):
    """The traced verify/commit program: draft, score, accept the
    longest draft==argmax prefix, scatter the committed window back
    into the history ring — all in-graph."""
    n, gamma = tokens.shape[0], 3
    history = history.at[jnp.arange(n), phys].set(tokens)
    drafts = _ngram_draft(history, phys + 1, gamma)
    verify = jnp.concatenate([tokens[:, None], drafts], axis=1)
    t_logits = logits_table[verify]
    y = jnp.argmax(t_logits, axis=-1).astype(jnp.int32)
    m = drafts == y[:, :gamma]
    n_r = jnp.sum(jnp.cumprod(m.astype(jnp.int32), axis=1), axis=1)
    n_r = jnp.where(active, n_r, 0)
    win = jnp.where(jnp.arange(gamma + 1)[None] < (n_r + 1)[:, None],
                    y, 0)
    history = jax.vmap(
        lambda row, w, p: jax.lax.dynamic_update_slice(row, w, (p,)))(
        history, win, phys + 1)
    return history, n_r, win


def host_commit(state, logits_table):
    """Scheduler-side tick driver: the ONLY place device values cross
    to the host, and the only place metrics move."""
    history, tokens, phys, active = state
    with span("fx/spec_tick"):
        history, n_r, win = spec_tick(history, tokens, phys, active,
                                      logits_table)
        n_r = np.array(n_r)          # host sync AFTER the jit boundary
        win = np.array(win)
    commit = np.where(active, n_r + 1, 0)
    DRAFTED.inc(3 * int(np.asarray(active).sum()))
    ACCEPTED.inc(int(n_r[np.asarray(active)].sum()))
    committed = [list(map(int, win[i, :commit[i]]))
                 for i in range(len(commit))]
    phys = np.asarray(phys) + commit
    return (history, win[np.arange(len(commit)),
                         np.maximum(commit - 1, 0)],
            phys.astype(np.int32), active), committed
