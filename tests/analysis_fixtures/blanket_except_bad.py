"""known-bad fixture: unannotated blanket handlers (3 findings)."""


def load(path):
    try:
        return open(path).read()
    except Exception:
        return None


def probe(fn):
    try:
        fn()
    except:  # a bare handler
        pass


def tuple_handler(fn):
    try:
        fn()
    except (ValueError, BaseException):
        return None
