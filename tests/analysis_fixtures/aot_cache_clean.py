"""known-clean fixture: the AOT cache idiom (docs/aot_cache.md) — every
cache side effect (metric bumps, file I/O, host transfers of results)
happens strictly OUTSIDE traced code, between jit boundaries.

Mirrors `fengshen_tpu/aot/cache.py` internals: the traced function is
pure; lowering/compiling/deserializing and the hit/miss/error counters
run on the host around it. Neither `metrics-in-traced-code` nor
`blocking-transfer` may fire here — if either does, the analyzer would
also flag the real cache module and block the merge gate.
"""

import hashlib
import pickle

import jax
import numpy as np

from fengshen_tpu.observability import get_registry, span

REG = get_registry()
HITS = REG.counter("fx_aot_hits_total", "hits", labelnames=("fn",))
MISSES = REG.counter("fx_aot_misses_total", "misses", labelnames=("fn",))
ERRORS = REG.counter("fx_aot_errors_total", "errors", labelnames=("fn",))


def decode_step(params, tokens, mask):
    # the traced program: pure array math, no metrics, no host pulls
    logits = tokens[:, None] * params["scale"]
    return (logits * mask[:, None]).sum(-1)


def fetch_or_compile(name, store, *args):
    """cached_compile's shape: lower → hash → load-or-compile, with the
    counters bumped on the HOST between jit boundaries."""
    jitted = jax.jit(decode_step)
    with span("aot/lower"):
        lowered = jitted.lower(*args)
    key = hashlib.sha256(lowered.as_text().encode()).hexdigest()
    blob = store.get(key)
    if blob is not None:
        try:
            with span("aot/deserialize"):
                exe = pickle.loads(blob)
            HITS.labels(name).inc()
            return exe
        except (pickle.UnpicklingError, ValueError, EOFError):
            # a corrupt blob silently recompiles — count it, never raise
            ERRORS.labels(name).inc()
    MISSES.labels(name).inc()
    with span("aot/compile"):
        compiled = lowered.compile()
    return compiled


def run_one(store, params, tokens, mask):
    exe = fetch_or_compile("serving/decode", store, params, tokens, mask)
    out = exe(params, tokens, mask)
    # host sync AFTER dispatch, outside any traced context
    return np.asarray(out)
