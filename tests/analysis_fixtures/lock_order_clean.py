"""Clean fixture for `lock-order`: the same two-class shape as the bad
twin, deadlock-free because the dump path snapshots under its own lock
and crosses into the engine only AFTER releasing it — one consistent
engine-before-recorder order package-wide."""

import threading


class Engine:
    def __init__(self, recorder: "Recorder"):
        self._cv = threading.Condition()
        self.recorder = recorder
        self.ticks = 0

    def tick(self):
        with self._cv:
            self.ticks += 1
            self.recorder.record(self.ticks)

    def snapshot(self):
        with self._cv:
            return self.ticks


class Recorder:
    def __init__(self, engine: "Engine"):
        self._lock = threading.Lock()
        self.engine = engine
        self.events = []

    def record(self, event):
        with self._lock:
            self.events.append(event)

    def dump(self):
        with self._lock:
            events = list(self.events)   # snapshot, then release
        return (events, self.engine.snapshot())
