"""known-clean fixture: the preemption-tolerance idiom (ISSUE 16,
docs/fault_tolerance.md "Preemption runbook") — drain-time lane
evacuation and resume-from-token-k are EAGER host-orchestrated work
between jit boundaries. The commit journal appends on the scheduler
thread under a plain `threading.Lock` (never inside traced code), the
evacuation export gathers the committed prefix eagerly (zero new
jitted programs: the engine's pinned compile counts must survive a
drain), the push to the adopting peer is a blocking HTTP call on the
drain thread, and the resume prefill is host-side token concatenation
feeding the SAME bucketed prefill program a fresh admission uses. The
tempting regressions this fixture guards: journaling or bumping the
`fstpu_evac_*`/`fstpu_resume_*` counters inside a traced tick
(metrics-in-traced-code), pushing an evacuated lane from traced code
(blocking-transfer), jitting the resume-prefix concat (a new program
per cut point — compile-count drift), or branching traced code on the
device-side cursor of the evacuating lane (host-divergence).

Mirrors `fengshen_tpu/serving/engine.py`'s journal + resume admission
and `fengshen_tpu/disagg/coordinator.py`'s `evacuate_all`: if a rule
fires here, it would also flag the real modules and block the merge
gate.
"""

import base64
import hashlib
import json
import threading

import jax
import jax.numpy as jnp
import numpy as np

from fengshen_tpu.observability import get_registry

REG = get_registry()
EVAC_LANES = REG.counter("fx_evac_lanes_total",
                         "drain-time lane evacuations by outcome",
                         labelnames=("outcome",))
RESUME_TOKENS = REG.counter("fx_resume_tokens_total",
                            "committed tokens reused by resume "
                            "prefills instead of re-decoded")

_JOURNAL_LOCK = threading.Lock()
_JOURNAL = {}
_JOURNAL_RING = 4


@jax.jit
def decode_tick(cache, tokens, phys):
    """The per-tick decode body: pure scatters — the journal, the
    evacuation push, and every counter stay OUT of here."""
    n = tokens.shape[0]
    cache = cache.at[jnp.arange(n), phys].set(tokens)
    return cache, (tokens + 1).astype(jnp.int32)


def journal_commit(request_id, token):
    """Host-side commit-journal append on the scheduler thread, under
    a plain lock, bounded like the engine's ring — a SIGKILL later
    serves `resume_tokens` from exactly this."""
    with _JOURNAL_LOCK:
        _JOURNAL.setdefault(request_id, []).append(int(token))
        while len(_JOURNAL) > _JOURNAL_RING:
            _JOURNAL.pop(next(iter(_JOURNAL)))


def export_evacuating_lane(cache, slot, cursor):
    """EAGER gather of the committed prefix at drain time: host-side
    jnp outside any jit (the drain adds zero compiled programs), then
    checksummed base64 framing — plain bytes work on the drain
    thread. `cursor` is a HOST int (the engine's per-lane host
    cursor), never a device value traced code branched on."""
    lane = np.asarray(jax.lax.slice_in_dim(
        jnp.take(cache, slot, axis=0), 0, cursor, axis=0))
    body = {"shape": list(lane.shape), "dtype": str(lane.dtype),
            "data": base64.b64encode(lane.tobytes()).decode("ascii")}
    raw = json.dumps(body, sort_keys=True).encode()
    body["checksum"] = hashlib.sha256(raw).hexdigest()
    return body


def evacuate_with_fallback(payload, push, finish_locally):
    """The drain loop's per-lane ladder: the blocking push and the
    outcome counter live on the drain thread, strictly between jit
    boundaries — a refused adoption is a counted local finish, never
    a client error."""
    try:
        push(payload)
        EVAC_LANES.labels("adopted").inc()
        return "adopted"
    except OSError:
        EVAC_LANES.labels("fallback").inc()
        finish_locally()
        return "fallback"


def resume_prefill_ids(prompt, resume_tokens):
    """Host-side resume admission: prompt + committed-prefix concat in
    numpy, feeding the SAME bucketed prefill program a fresh admission
    uses (all but the last resumed token; the first tick re-commits
    it) — recovering a request compiles nothing new."""
    ids = np.concatenate([np.asarray(prompt, np.int32),
                          np.asarray(resume_tokens[:-1], np.int32)])
    RESUME_TOKENS.inc(len(resume_tokens))
    return ids
