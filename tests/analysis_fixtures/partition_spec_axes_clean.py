"""known-clean fixture: every axis name exists on the mesh."""

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

RULES = [
    ("embed", P("tensor", "fsdp")),
    ("mlp", P(("data", "fsdp"), "tensor")),
    ("norm", P(None)),
    ("moe", P("expert", None, "sequence")),
]


def shard(mesh, x, axes):
    # axis names flowing in as VARIABLES are out of scope (not literals)
    spec = jax.sharding.PartitionSpec(*axes)
    return jax.device_put(x, NamedSharding(mesh, spec))


def stage_spec():
    return P("pipe", ("data", "fsdp"))


# declarative sharding tables (docs/sharding.md): logical names from
# sharding/axes.py, mesh axes literal or imported (imported names are
# definitionally valid)
GOOD_PARAM_LOGICAL_AXES = [
    ("q_proj/kernel", ("embed", "heads")),
    ("experts_down", ("expert", "mlp", None)),
    ("norm", ("norm",)),
    (".*", (None,)),
]

GOOD_LOGICAL_AXIS_RULES = (
    ("batch", ("data", "fsdp")),
    ("heads", "tensor"),
    ("relpos", None),
)
