"""known-clean fixture: every axis name exists on the mesh."""

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

RULES = [
    ("embed", P("tensor", "fsdp")),
    ("mlp", P(("data", "fsdp"), "tensor")),
    ("norm", P(None)),
    ("moe", P("expert", None, "sequence")),
]


def shard(mesh, x, axes):
    # axis names flowing in as VARIABLES are out of scope (not literals)
    spec = jax.sharding.PartitionSpec(*axes)
    return jax.device_put(x, NamedSharding(mesh, spec))


def stage_spec():
    return P("pipe", ("data", "fsdp"))
