"""Known-bad fixture for `api-surface-parity`.

The fastapi surface registers `/healthz` and `/infer`; the stdlib
twin only dispatches `/healthz` — `POST /infer` would 404 on the
dependency-free server.
"""

from http.server import BaseHTTPRequestHandler

from fastapi import FastAPI

app = FastAPI()


@app.get("/healthz")
def healthz():
    return {"ok": True}


@app.post("/infer")
def infer(payload: dict):
    return {"text": ""}


class Handler(BaseHTTPRequestHandler):
    def do_GET(self):
        if self.path == "/healthz":
            self.send_response(200)
        else:
            self.send_response(404)
