"""Clean fixture for `unguarded-shared-state`: every escape hatch the
rule promises — __init__ writes, guard inference through call chains,
the `*_locked` convention, and scheduler-thread confinement."""

import threading


class DisciplinedQueue:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []          # __init__ writes need no lock
        self._accepted = 0
        self._ticks = 0
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def push(self, item):
        with self._lock:
            self._items.append(item)
            self._bump_accepted_locked()

    def _bump_accepted_locked(self):
        # convention: caller holds self._lock
        self._accepted += 1

    def drain(self):
        with self._lock:
            return self._drain_inner()

    def _drain_inner(self):
        # guard inference: only ever called under the lock
        out, self._items = self._items, []
        return out

    def _loop(self):
        # scheduler-thread confinement: _ticks is only ever touched
        # on the thread this class owns
        while True:
            self._ticks += 1
            self._tick_once()

    def _tick_once(self):
        self._ticks += 1
