"""Known-bad fixture for `unguarded-shared-state`.

Seeded from the PR 11 JsonlSink bug shape: a sink whose writer list
is appended under its lock on the hot path, but swapped/cleared with
no lock from a maintenance method called off the flush thread —
interleaved writers corrupted the JSONL stream until review caught it.
"""

import threading


class Sink:
    def __init__(self):
        self._lock = threading.Lock()
        self._buffer = []
        self._dropped = 0

    def emit(self, rec):
        with self._lock:
            self._buffer.append(rec)

    def flush(self):
        with self._lock:
            out, self._buffer = self._buffer, []
            self._dropped = 0
        return out

    def trim(self, keep):
        # BUG: races emit()/flush() — mutates the buffer and the
        # dropped counter with no lock
        self._dropped += max(0, len(self._buffer) - keep)
        self._buffer = self._buffer[-keep:]


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    def bump(self):
        with self._lock:
            self._n += 1

    def reset(self):
        self._n = 0  # BUG: unguarded store races bump()'s RMW
