"""Known-bad fixture for `lock-order`.

Seeded from the engine/recorder shape: the scheduler holds its
condition and calls into the recorder (recorder lock), while the
recorder's dump path holds its own lock and calls back into the
engine — the two-lock inversion is only visible across the pair of
classes, one hop of the call graph apart.
"""

import threading


class Engine:
    def __init__(self, recorder: "Recorder"):
        self._cv = threading.Condition()
        self.recorder = recorder
        self.ticks = 0

    def tick(self):
        with self._cv:
            self.ticks += 1
            # order A->B: engine cv, then recorder lock
            self.recorder.record(self.ticks)

    def snapshot(self):
        with self._cv:
            return self.ticks


class Recorder:
    def __init__(self, engine: "Engine"):
        self._lock = threading.Lock()
        self.engine = engine
        self.events = []

    def record(self, event):
        with self._lock:
            self.events.append(event)

    def dump(self):
        with self._lock:
            # order B->A: recorder lock, then engine cv — ABBA
            return (list(self.events), self.engine.snapshot())
