"""known-clean fixture: deterministic iteration orders."""

import jax
import jax.numpy as jnp


def gather_stats(params, skip):
    stats = {}
    for name in sorted(set(params) - set(skip)):  # pinned order
        stats[name] = jax.lax.psum(params[name], "data")
    return stats


def stack_overlap(a, b):
    out = []
    for key in sorted(a.keys() & b.keys()):
        out.append(jnp.stack([a[key], b[key]]))
    return out


def walk_config(cfg):
    total = 0.0
    # plain dict iteration is insertion-ordered: deterministic
    for key in cfg:
        total += cfg[key]
    # a set loop whose body is pure host arithmetic is also fine
    for flag in {"a", "b"}:
        total += len(flag)
    return total
