"""known-bad fixture: device->host syncs inside traced step code."""

import jax
import numpy as np


@jax.jit
def summarize(metrics):
    return metrics["loss"].item()  # concretizes a tracer


def train_step(state, batch):
    loss = (batch["x"] ** 2).mean()
    host_loss = float(loss)  # blocking scalar pull in the hot path
    arr = np.asarray(batch["x"])  # forces host round-trip
    got = jax.device_get(loss)
    return state, host_loss + arr.sum() + got


def outer(xs):
    def body(carry, x):
        return carry + int(x.sum()), None

    return jax.lax.scan(body, 0, xs)
