"""known-clean fixture: the fleet-router idiom (ISSUE 10,
docs/fleet.md) — ALL routing state lives on the host. The router
itself is pure stdlib (clocks, seeded backoff jitter, threading,
per-replica counters), which is only safe because none of it ever
enters a traced program: the replicas' jitted decode stays a pure
device function, and the router talks to it over HTTP from outside
every jit boundary. The tempting regressions this fixture guards:
leaking the backoff `random.Random` or `time.monotonic()` into traced
code (host-divergence), pulling a device value per routed request to
compute occupancy (blocking-transfer), or bumping the
`fstpu_fleet_*` counters inside a traced helper
(metrics-in-traced-code).

Mirrors `fengshen_tpu/fleet/router.py`'s pick/retry/breaker loop
around `fengshen_tpu/serving/engine.py`'s tick: if a rule fires here,
it would also flag the real modules and block the merge gate.
"""

import random
import time

import jax
import jax.numpy as jnp
import numpy as np

from fengshen_tpu.observability import get_registry

REG = get_registry()
RETRIES = REG.counter("fx_fleet_retries_total", "retries by reason",
                      labelnames=("reason",))
REPLICAS = REG.gauge("fx_fleet_replicas", "replicas by state",
                     labelnames=("state",))


@jax.jit
def replica_decode_tick(cache, tokens, phys, active):
    """What a replica runs per tick: pure gathers/scatters — the
    router never adds clocks, rng, or metric mutation in here."""
    n = tokens.shape[0]
    cache = cache.at[jnp.arange(n), phys].set(tokens)
    nxt = jnp.where(active, tokens + 1, 0).astype(jnp.int32)
    return cache, nxt


def pick_replica(replicas):
    """Host-side placement: least occupancy from POLLED stats (plain
    dict math — never a device read), ties by index."""
    best = None
    for rep in replicas:
        occ = (rep["slots_active"] + rep["queue_depth"]) / max(
            rep["num_slots"], 1)
        if rep["healthy"] and (best is None or occ < best[0]):
            best = (occ, rep)
    return None if best is None else best[1]


def route_with_retries(replicas, send, max_retries=2,
                       rng=random.Random(0), clock=time.monotonic,
                       sleep=time.sleep):
    """Host-side retry loop: the seeded jitter rng and the clock live
    OUT here, between HTTP calls — nothing below is traced."""
    tried = []
    for attempt in range(max_retries + 1):
        rep = pick_replica([r for r in replicas if r not in tried])
        if rep is None:
            break
        tried.append(rep)
        t0 = clock()
        ok = send(rep)
        if ok:
            return clock() - t0
        rep["consecutive_failures"] += 1
        if rep["consecutive_failures"] >= 3:
            rep["healthy"] = False      # breaker opens, host-side
            REPLICAS.labels("broken").set(
                sum(1 for r in replicas if not r["healthy"]))
        if attempt < max_retries:
            RETRIES.labels("connect").inc()
            sleep(0.01 * (0.5 + rng.random() / 2))
    return None


def drive_replica(state, tokens):
    """The replica-side driver the router's request lands on: one
    traced tick, host sync strictly after the jit boundary."""
    cache, phys, active = state
    cache, nxt = replica_decode_tick(cache, tokens, phys, active)
    return cache, np.array(nxt)        # host sync OUTSIDE the jit
