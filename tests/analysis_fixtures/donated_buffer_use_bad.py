"""Known-bad fixture for `donated-buffer-use`.

The train step donates its state argument (`donate_argnums=(0,)`), so
XLA may reuse the buffer for the output; reading `state` after the
donating call observes freed/aliased memory on TPU.
"""

import jax


def _step_impl(state, batch):
    return state + batch


class Stepper:
    def __init__(self):
        self._step = jax.jit(_step_impl, donate_argnums=(0,))

    def run(self, state, batch):
        new_state = self._step(state, batch)
        stale = state.sum()        # BAD: reads the donated buffer
        return new_state, stale
