"""known-bad fixture: host-varying values inside traced code."""

import os
import random
import time
import uuid

import jax
import jax.numpy as jnp


@jax.jit
def noisy_step(x):
    return x + random.random()  # baked per-host constant


def train_step(state, batch):
    seed = time.time()  # traced by name convention
    tag = uuid.uuid4().int
    scale = float(os.environ["LOSS_SCALE"])
    return state, batch["x"] * seed * scale + tag


def outer(xs):
    def body(carry, x):
        return carry + x * time.monotonic(), None

    return jax.lax.scan(body, jnp.zeros(()), xs)
