"""Known-bad fixture for `blocking-under-lock`.

Seeded from the fleet-router shape: holding the placement lock across
a replica HTTP round-trip serialises the whole fleet on one slow
replica. Includes the transitive chain the project call graph must
follow: with-lock -> local helper -> module helper -> urlopen.
"""

import threading
import time
import urllib.request


class Router:
    def __init__(self):
        self._lock = threading.Lock()
        self._replicas = []

    def probe(self, url):
        with self._lock:
            # BUG: direct network I/O inside the critical section
            return urllib.request.urlopen(url)

    def rebalance(self):
        with self._lock:
            # BUG: transitive — _refresh() ends in a blocking fetch
            self._refresh()

    def _refresh(self):
        for rep in self._replicas:
            _fetch_health(rep)

    def throttle(self):
        with self._lock:
            time.sleep(0.5)  # BUG: sleeping while others wait


def _fetch_health(url):
    return urllib.request.urlopen(url)
