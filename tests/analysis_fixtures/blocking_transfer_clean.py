"""known-clean fixture: scalars leave the device OUTSIDE the trace."""

import jax
import jax.numpy as jnp


@jax.jit
def train_loss(params, batch):
    return jnp.mean((batch["x"] - params["w"]) ** 2)


def fit(params, batches):
    for batch in batches:
        loss = train_loss(params, batch)
        # host read AFTER dispatch, outside the traced function: fine
        print("loss:", float(loss), loss.item())
    return params
