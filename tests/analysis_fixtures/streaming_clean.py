"""known-clean fixture: the streaming-tier idiom (ISSUE 20,
docs/streaming.md) — token-by-token delivery is HOST work on the
scheduler and reader threads, while the per-lane RNG that makes
sampled streams reproducible lives entirely INSIDE the decode graph.
The per-tick key split is traced (`jax.vmap(jax.random.split)` over
the lane-key ring — a pure function of the carried keys, no host
randomness under trace), the commit-then-publish order runs on the
scheduler thread under plain locks (journal append, then stream
publish under a per-stream condition), and SSE framing + the TTFB
observation happen on the reader's delivery thread. The tempting
regressions this fixture guards: publishing stream tokens or bumping
the `fstpu_stream_*` counters inside the traced tick
(metrics-in-traced-code), writing SSE bytes to a socket from traced
code (blocking-transfer), branching traced code on a host-side stream
state flag (host-divergence), or seeding the lane key from a host
`random.random()` under trace (nondet — the lane key must fold from
the pinned request seed so a retried request replays byte-identical).

Mirrors `fengshen_tpu/streaming/stream.py`'s publish/events split and
`fengshen_tpu/serving/engine.py`'s key ring + `_sync_stream`: if a
rule fires here, it would also flag the real modules and block the
merge gate.
"""

import threading

import jax
import jax.numpy as jnp

from fengshen_tpu.observability import get_registry

REG = get_registry()
STREAM_TOKENS = REG.counter("fx_stream_tokens_total",
                            "tokens published to live streams")
STREAM_TTFB = REG.histogram("fx_stream_ttfb_seconds",
                            "submit-to-first-delivered-byte")


@jax.jit
def decode_tick(cache, tokens, keys):
    """The per-tick decode body: the lane-key ring splits IN-GRAPH
    (carried state, pure function of the folded request seeds) — the
    stream publish, the SSE write, and every counter stay OUT of
    here."""
    split = jax.vmap(jax.random.split)(keys)
    keys_out, tick_keys = split[:, 0], split[:, 1]
    n = tokens.shape[0]
    cache = cache.at[jnp.arange(n)].set(tokens)
    nxt = jax.vmap(
        lambda k, t: jax.random.categorical(
            k, jnp.ones((8,)) * t.astype(jnp.float32)))(
        tick_keys, tokens)
    return cache, nxt.astype(jnp.int32), keys_out


def admission_key(base_key, request_seed):
    """Host-side lane-key derivation at admission: fold the PINNED
    request seed into the engine's base key — placement-independent,
    so a fleet retry under the same request id replays the same
    stream."""
    base = jax.random.fold_in(base_key, request_seed)
    _consume, lane_key = jax.random.split(base)
    return lane_key


class LiveStream:
    """One request's feed: scheduler publishes under a plain condition
    AFTER the commit journal append; the reader drains on its own
    thread — a stalled client never blocks the scheduler."""

    def __init__(self):
        self._cond = threading.Condition()
        self._tokens = []
        self.closed = False

    def publish(self, snapshot, closed=False):
        with self._cond:
            new = snapshot[len(self._tokens):]
            self._tokens.extend(int(t) for t in new)
            self.closed = self.closed or closed
            if new or closed:
                self._cond.notify_all()
        if new:
            STREAM_TOKENS.inc(len(new))
        return len(new)

    def drain_from(self, pos):
        with self._cond:
            while len(self._tokens) <= pos and not self.closed:
                self._cond.wait(timeout=1.0)
            return self._tokens[pos:], self.closed


def deliver_sse(stream, write, clock, t_submit):
    """The reader thread's delivery loop: byte framing and the TTFB
    observation are host work BETWEEN jit boundaries; the blocking
    socket write happens here, never under trace and never under the
    stream's condition."""
    pos, first = 0, True
    while True:
        batch, closed = stream.drain_from(pos)
        if batch and first:
            STREAM_TTFB.observe(clock() - t_submit)
            first = False
        for tok in batch:
            write(b"id: %d\nevent: token\ndata: {\"token\": %d}\n\n"
                  % (pos, tok))
            pos += 1
        if closed:
            write(b"event: done\ndata: {}\n\n")
            return pos
