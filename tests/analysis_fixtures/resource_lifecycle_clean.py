"""Clean fixture for `resource-lifecycle`.

The three sanctioned shapes: release in a `finally`; transfer
ownership into a container (the slot table owns the blocks from then
on); let a `with` statement manage the file handle.
"""

import json


class Pool:
    def __init__(self, allocator, ladder, slots):
        self._allocator = allocator
        self.ladder = ladder
        self._slots = slots

    def admit(self, req, need):
        blocks = self._allocator.alloc(need)
        if blocks is None:
            return None                     # exhaustion: nothing held
        try:
            return self.ladder.pad_prompt(req)
        finally:
            self._allocator.free(blocks)

    def adopt(self, slot, need):
        blocks = self._allocator.alloc(need)
        if blocks is None:
            return False
        self._slots[slot] = blocks          # ownership transfer
        self.ladder.commit(slot)
        return True


def append_record(path, record):
    with open(path, "a", encoding="utf-8") as out:
        out.write(json.dumps(record) + "\n")
