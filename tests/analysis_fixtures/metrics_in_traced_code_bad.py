"""known-bad fixture: registry mutations reached from traced code."""

import jax

from fengshen_tpu.observability import get_registry

REG = get_registry()
STEPS = REG.counter("fx_steps_total", "steps")
LOSS_HIST = REG.histogram("fx_loss", "loss samples")


class Stats:
    def __init__(self):
        self.tokens = REG.counter("fx_tokens_total", "tokens",
                                  labelnames=("stage",))


STATS = Stats()


@jax.jit
def jitted_step(x):
    STEPS.inc()  # records at trace time only
    return x * 2


def train_step(state, batch):
    # traced-by-convention name: every mutation below is trace-frozen
    LOSS_HIST.observe(float(batch["x"].mean()))
    STATS.tokens.labels("train").inc(batch["x"].size)
    REG.gauge("fx_lr", "lr").set(0.1)
    return state


def outer(xs):
    def body(carry, x):
        STEPS.inc()  # scan body is traced
        return carry + x, None

    return jax.lax.scan(body, 0.0, xs)
