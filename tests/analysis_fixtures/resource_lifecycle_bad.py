"""Known-bad fixture for `resource-lifecycle`.

`admit` leaks its KV blocks when `pad_prompt` raises between the
alloc and the free; `recycle` returns the same blocks to the pool
twice on one path.
"""


class Pool:
    def __init__(self, allocator, ladder):
        self._allocator = allocator
        self.ladder = ladder

    def admit(self, req, need):
        blocks = self._allocator.alloc(need)
        if blocks is None:
            return None
        row = self.ladder.pad_prompt(req)   # BAD: raises -> blocks leak
        self._allocator.free(blocks)
        return row

    def recycle(self, need):
        blocks = self._allocator.alloc(need)
        if blocks is None:
            return
        self._allocator.free(blocks)
        self._allocator.free(blocks)        # BAD: double release
