"""Weight-importer round-trip tests for the round-2 converter batch
(bert, pegasus, longformer, clip, deltalm, zen, hubert, SD) — forward
parity against HF torch oracles where transformers ships the family, and
structural load tests otherwise (pattern: tests/test_llama.py)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest


def _np(x):
    return x.detach().cpu().numpy() if hasattr(x, "detach") else np.asarray(x)


def test_bert_convert_forward_parity():
    torch = pytest.importorskip("torch")
    import transformers

    from fengshen_tpu.models.bert import BertConfig, BertForMaskedLM
    from fengshen_tpu.models.bert.convert import torch_to_params

    hf_cfg = transformers.BertConfig(
        vocab_size=120, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, hidden_act="gelu")
    torch.manual_seed(0)
    tm = transformers.BertForMaskedLM(hf_cfg).eval()

    cfg = BertConfig(vocab_size=120, hidden_size=32, num_hidden_layers=2,
                     num_attention_heads=4, intermediate_size=64,
                     max_position_embeddings=64, hidden_act="gelu",
                     dtype="float32")
    params = torch_to_params(tm.state_dict(), cfg)
    ids = np.array([[2, 17, 9, 42, 7, 99, 1, 5]], np.int32)
    logits = BertForMaskedLM(cfg).apply({"params": params},
                                        jnp.asarray(ids))
    with torch.no_grad():
        ref = tm(torch.tensor(ids, dtype=torch.long)).logits.numpy()
    np.testing.assert_allclose(np.asarray(logits), ref, atol=2e-3)


def test_pegasus_convert_forward_parity():
    torch = pytest.importorskip("torch")
    import transformers

    from fengshen_tpu.models.pegasus import PegasusConfig
    from fengshen_tpu.models.pegasus.modeling_pegasus import (
        PegasusForConditionalGeneration)
    from fengshen_tpu.models.pegasus.convert import torch_to_params

    hf_cfg = transformers.PegasusConfig(
        vocab_size=120, d_model=32, encoder_layers=2, decoder_layers=2,
        encoder_attention_heads=4, decoder_attention_heads=4,
        encoder_ffn_dim=64, decoder_ffn_dim=64,
        max_position_embeddings=64, activation_function="relu",
        scale_embedding=False)
    torch.manual_seed(0)
    tm = transformers.PegasusForConditionalGeneration(hf_cfg).eval()

    cfg = PegasusConfig(vocab_size=120, d_model=32, encoder_layers=2,
                        decoder_layers=2, encoder_attention_heads=4,
                        decoder_attention_heads=4, encoder_ffn_dim=64,
                        decoder_ffn_dim=64, max_position_embeddings=64,
                        activation_function="relu", scale_embedding=False,
                        dtype="float32")
    params = torch_to_params(tm.state_dict(), cfg)
    enc = np.array([[2, 17, 9, 42]], np.int32)
    dec = np.array([[0, 5, 7, 1]], np.int32)
    logits = PegasusForConditionalGeneration(cfg).apply(
        {"params": params}, jnp.asarray(enc), jnp.asarray(dec))
    with torch.no_grad():
        ref = tm(input_ids=torch.tensor(enc, dtype=torch.long),
                 decoder_input_ids=torch.tensor(dec, dtype=torch.long)
                 ).logits.numpy()
    np.testing.assert_allclose(np.asarray(logits), ref, atol=2e-3)


def test_longformer_convert_window_parity():
    """Pure sliding-window case (no globals, no padding): our banded
    attention equals HF LongformerModel, so the converter is verified by
    forward parity."""
    torch = pytest.importorskip("torch")
    import transformers

    from fengshen_tpu.models.longformer.modeling_longformer import (
        LongformerConfig, LongformerModel)
    from fengshen_tpu.models.longformer.convert import torch_to_params

    hf_cfg = transformers.LongformerConfig(
        vocab_size=120, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=66, attention_window=[8, 8],
        pad_token_id=0)
    torch.manual_seed(0)
    tm = transformers.LongformerModel(hf_cfg, add_pooling_layer=False).eval()

    cfg = LongformerConfig(
        vocab_size=120, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, attention_window=8, dtype="float32")
    state = {f"longformer.{k}": v for k, v in tm.state_dict().items()}
    params = torch_to_params(state, cfg)["longformer"]

    seq = 16  # multiple of the window (HF requirement)
    ids = np.array([np.arange(2, 2 + seq)], np.int32)
    hidden, _ = LongformerModel(cfg, add_pooling_layer=False).apply(
        {"params": params}, jnp.asarray(ids))
    with torch.no_grad():
        # HF positions are offset by pad_token_id+1=1+... (RoBERTa style);
        # pin them to match arange used on the flax side
        pos = torch.arange(2, 2 + seq)[None]
        ref = tm(torch.tensor(ids, dtype=torch.long),
                 position_ids=pos).last_hidden_state.numpy()
    np.testing.assert_allclose(np.asarray(hidden), ref, atol=3e-3)


def test_clip_vision_convert_forward_parity():
    torch = pytest.importorskip("torch")
    import transformers

    from fengshen_tpu.models.clip import CLIPVisionConfig
    from fengshen_tpu.models.clip.modeling_taiyi_clip import (
        CLIPVisionTransformer)
    from fengshen_tpu.models.clip.convert import vision_to_params

    hf_cfg = transformers.CLIPVisionConfig(
        hidden_size=32, intermediate_size=64, num_hidden_layers=2,
        num_attention_heads=4, image_size=32, patch_size=8,
        projection_dim=16)
    torch.manual_seed(0)
    tm = transformers.CLIPVisionModel(hf_cfg).eval()

    cfg = CLIPVisionConfig(hidden_size=32, intermediate_size=64,
                           num_hidden_layers=2, num_attention_heads=4,
                           image_size=32, patch_size=8, projection_dim=16,
                           dtype="float32")
    params = vision_to_params(tm.state_dict(), cfg)
    rng = np.random.RandomState(0)
    pixels = rng.randn(1, 32, 32, 3).astype(np.float32)
    hidden, pooled = CLIPVisionTransformer(cfg).apply(
        {"params": params}, jnp.asarray(pixels))
    with torch.no_grad():
        ref = tm(torch.tensor(pixels.transpose(0, 3, 1, 2)))
    np.testing.assert_allclose(np.asarray(pooled),
                               ref.pooler_output.numpy(), atol=2e-3)


def _fake_state(shapes):
    rng = np.random.RandomState(0)
    return {k: rng.randn(*v).astype(np.float32) * 0.02 for k, v in
            shapes.items()}


def test_deltalm_convert_structural_roundtrip():
    """No torch DeltaLM oracle exists in this env; verify that a synthetic
    reference-named state dict converts into exactly the flax param tree
    and that the model runs with it."""
    from fengshen_tpu.models.deltalm import (DeltaLMConfig,
                                             DeltaLMForConditionalGeneration)
    from fengshen_tpu.models.deltalm.convert import torch_to_params

    cfg = DeltaLMConfig.small_test_config()
    model = DeltaLMForConditionalGeneration(cfg)
    ids = jnp.zeros((1, 4), jnp.int32)
    init = model.init(jax.random.PRNGKey(0), ids, ids)["params"]

    d, f = cfg.d_model, cfg.encoder_ffn_dim
    shapes = {"encoder.embed_tokens.weight": (cfg.vocab_size, d),
              "encoder.embed_positions.weight": (
                  cfg.max_position_embeddings + 2, d)}
    for pre, n in (("encoder", cfg.encoder_layers),
                   ("decoder", cfg.decoder_layers)):
        shapes[f"{pre}.layernorm_embedding.weight"] = (d,)
        shapes[f"{pre}.layernorm_embedding.bias"] = (d,)
        shapes[f"{pre}.layer_norm.weight"] = (d,)
        shapes[f"{pre}.layer_norm.bias"] = (d,)
        for i in range(n):
            p = f"{pre}.layers.{i}"
            for att in (["self_attn"] if pre == "encoder" else
                        ["self_attn", "encoder_attn"]):
                for proj in ("q_proj", "k_proj", "v_proj", "out_proj"):
                    shapes[f"{p}.{att}.{proj}.weight"] = (d, d)
                    shapes[f"{p}.{att}.{proj}.bias"] = (d,)
                shapes[f"{p}.{att}_layer_norm.weight"] = (d,)
                shapes[f"{p}.{att}_layer_norm.bias"] = (d,)
            fcs = ("fc1", "fc2") if pre == "encoder" else \
                ("fc1", "fc2", "fc3", "fc4")
            for fc in fcs:
                wide = fc in ("fc1", "fc3")
                shapes[f"{p}.{fc}.weight"] = (f, d) if wide else (d, f)
                shapes[f"{p}.{fc}.bias"] = (f,) if wide else (d,)
            shapes[f"{p}.final_layer_norm.weight"] = (d,)
            shapes[f"{p}.final_layer_norm.bias"] = (d,)
            if pre == "decoder":
                shapes[f"{p}.ffn_layer_norm.weight"] = (d,)
                shapes[f"{p}.ffn_layer_norm.bias"] = (d,)

    params = torch_to_params(_fake_state(shapes), cfg)
    # exact tree match with the flax init (same keys, same shapes)
    flat_init = jax.tree_util.tree_map(lambda x: x.shape, init)
    flat_conv = jax.tree_util.tree_map(lambda x: tuple(x.shape), params)
    # embed_positions row count may differ (fairseq +2 offset kept as-is)
    flat_init["embed_positions"] = flat_conv["embed_positions"]
    assert flat_init == flat_conv
    logits = model.apply({"params": params}, ids, ids)
    assert np.isfinite(np.asarray(logits)).all()


def test_zen_convert_structural_roundtrip():
    from fengshen_tpu.models.zen import ZenConfig, ZenModel
    from fengshen_tpu.models.zen.convert import torch_to_params

    cfg = ZenConfig.small_test_config()
    model = ZenModel(cfg)
    ids = jnp.zeros((1, 8), jnp.int32)
    ngram_ids = jnp.zeros((1, 4), jnp.int32)
    ngram_pos = jnp.zeros((1, 8, 4), jnp.int32)
    init = model.init(jax.random.PRNGKey(0), ids, ngram_ids,
                      ngram_pos)["params"]

    d, f = cfg.hidden_size, cfg.intermediate_size
    shapes = {
        "bert.embeddings.word_embeddings.weight": (cfg.vocab_size, d),
        "bert.embeddings.position_embeddings.weight": (
            cfg.max_position_embeddings, d),
        "bert.embeddings.token_type_embeddings.weight": (
            cfg.type_vocab_size, d),
        "bert.embeddings.LayerNorm.weight": (d,),
        "bert.embeddings.LayerNorm.bias": (d,),
        "bert.word_embeddings.word_embeddings.weight": (
            cfg.ngram_vocab_size, d),
        "bert.word_embeddings.token_type_embeddings.weight": (
            cfg.type_vocab_size, d),
        "bert.word_embeddings.LayerNorm.weight": (d,),
        "bert.word_embeddings.LayerNorm.bias": (d,),
        "bert.pooler.dense.weight": (d, d),
        "bert.pooler.dense.bias": (d,),
    }

    def bert_layer_shapes(p):
        for sub in ("attention.self.query", "attention.self.key",
                    "attention.self.value", "attention.output.dense"):
            shapes[f"{p}.{sub}.weight"] = (d, d)
            shapes[f"{p}.{sub}.bias"] = (d,)
        shapes[f"{p}.attention.output.LayerNorm.weight"] = (d,)
        shapes[f"{p}.attention.output.LayerNorm.bias"] = (d,)
        shapes[f"{p}.intermediate.dense.weight"] = (f, d)
        shapes[f"{p}.intermediate.dense.bias"] = (f,)
        shapes[f"{p}.output.dense.weight"] = (d, f)
        shapes[f"{p}.output.dense.bias"] = (d,)
        shapes[f"{p}.output.LayerNorm.weight"] = (d,)
        shapes[f"{p}.output.LayerNorm.bias"] = (d,)

    for i in range(cfg.num_hidden_layers):
        bert_layer_shapes(f"bert.encoder.layer.{i}")
    for i in range(cfg.num_ngram_layers):
        bert_layer_shapes(f"bert.encoder.word_layers.{i}")

    params = torch_to_params(_fake_state(shapes), cfg)
    assert jax.tree_util.tree_map(lambda x: x.shape, init) == \
        jax.tree_util.tree_map(lambda x: tuple(x.shape), params)
    hidden, pooled = model.apply({"params": params}, ids, ngram_ids,
                                 ngram_pos)
    assert np.isfinite(np.asarray(hidden)).all()


def test_hubert_convert_structural_roundtrip():
    from fengshen_tpu.models.hubert import HubertConfig, HubertModel
    from fengshen_tpu.models.hubert.convert import torch_to_params

    cfg = HubertConfig.small_test_config()
    model = HubertModel(cfg)
    wav = jnp.zeros((1, 400))
    init = model.init(jax.random.PRNGKey(0), wav)["params"]

    d = cfg.hidden_size
    shapes = {}
    in_ch = 1
    for i, (ch, k, s) in enumerate(cfg.conv_layers):
        shapes[f"feature_extractor.conv_layers.{i}.conv.weight"] = (
            ch, in_ch, k)
        in_ch = ch
    shapes["feature_extractor.conv_layers.0.layer_norm.weight"] = (
        cfg.conv_layers[0][0],)
    shapes["feature_extractor.conv_layers.0.layer_norm.bias"] = (
        cfg.conv_layers[0][0],)
    shapes["feature_projection.projection.weight"] = (d, in_ch)
    shapes["feature_projection.projection.bias"] = (d,)
    # HF order: layer_norm over the CONV dim, then project
    shapes["feature_projection.layer_norm.weight"] = (in_ch,)
    shapes["feature_projection.layer_norm.bias"] = (in_ch,)
    shapes["encoder.layer_norm.weight"] = (d,)
    shapes["encoder.layer_norm.bias"] = (d,)
    shapes["masked_spec_embed"] = (d,)
    # real HF/fairseq checkpoints use weight_norm(conv, dim=2):
    # g is (1, 1, K), one gain per kernel position
    shapes["encoder.pos_conv_embed.conv.weight_g"] = (
        1, 1, cfg.pos_conv_kernel)
    shapes["encoder.pos_conv_embed.conv.weight_v"] = (
        d, d // cfg.pos_conv_groups, cfg.pos_conv_kernel)
    shapes["encoder.pos_conv_embed.conv.bias"] = (d,)
    for i in range(cfg.num_hidden_layers):
        p = f"encoder.layers.{i}"
        for sub in ("attention.q_proj", "attention.k_proj",
                    "attention.v_proj", "attention.out_proj"):
            shapes[f"{p}.{sub}.weight"] = (d, d)
            shapes[f"{p}.{sub}.bias"] = (d,)
        shapes[f"{p}.layer_norm.weight"] = (d,)
        shapes[f"{p}.layer_norm.bias"] = (d,)
        shapes[f"{p}.feed_forward.intermediate_dense.weight"] = (
            cfg.intermediate_size, d)
        shapes[f"{p}.feed_forward.intermediate_dense.bias"] = (
            cfg.intermediate_size,)
        shapes[f"{p}.feed_forward.output_dense.weight"] = (
            d, cfg.intermediate_size)
        shapes[f"{p}.feed_forward.output_dense.bias"] = (d,)
        shapes[f"{p}.final_layer_norm.weight"] = (d,)
        shapes[f"{p}.final_layer_norm.bias"] = (d,)
    shapes["final_proj.weight"] = (cfg.num_clusters, d)
    shapes["final_proj.bias"] = (cfg.num_clusters,)

    params = torch_to_params(_fake_state(shapes), cfg)
    assert jax.tree_util.tree_map(lambda x: x.shape, init) == \
        jax.tree_util.tree_map(lambda x: tuple(x.shape), params)
    logits, _ = model.apply({"params": params}, wav)
    assert np.isfinite(np.asarray(logits)).all()


def test_sd_diffusers_to_original_keymap():
    """Key-arithmetic parity with the reference converter on representative
    keys (reference: convert_diffusers_to_original_stable_diffusion.py)."""
    from fengshen_tpu.models.stable_diffusion.convert import (
        convert_unet_state_dict, convert_vae_state_dict,
        diffusers_to_original)

    unet = {
        "time_embedding.linear_1.weight": np.zeros((4, 4)),
        "conv_in.weight": np.zeros((4, 4, 3, 3)),
        "down_blocks.0.resnets.0.norm1.weight": np.zeros((4,)),
        "down_blocks.0.resnets.1.time_emb_proj.weight": np.zeros((4, 4)),
        "down_blocks.1.attentions.0.proj_in.weight": np.zeros((4, 4)),
        "down_blocks.0.downsamplers.0.conv.weight": np.zeros((4, 4, 3, 3)),
        "up_blocks.2.resnets.2.conv_shortcut.weight": np.zeros((4, 4, 1, 1)),
        "mid_block.attentions.0.proj_out.weight": np.zeros((4, 4)),
        "mid_block.resnets.1.conv1.weight": np.zeros((4, 4, 3, 3)),
        "conv_norm_out.weight": np.zeros((4,)),
    }
    out = convert_unet_state_dict(unet)
    for key in ("time_embed.0.weight", "input_blocks.0.0.weight",
                "input_blocks.1.0.in_layers.0.weight",
                "input_blocks.2.0.emb_layers.1.weight",
                "input_blocks.4.1.proj_in.weight",
                "input_blocks.3.0.op.weight",
                "output_blocks.8.0.skip_connection.weight",
                "middle_block.1.proj_out.weight",
                "middle_block.2.in_layers.2.weight",
                "out.0.weight"):
        assert key in out, (key, sorted(out))

    vae = {
        "encoder.down_blocks.0.resnets.0.conv1.weight":
            np.zeros((4, 4, 3, 3)),
        "encoder.down_blocks.0.downsamplers.0.conv.weight":
            np.zeros((4, 4, 3, 3)),
        "decoder.up_blocks.1.resnets.2.conv_shortcut.weight":
            np.zeros((4, 4, 1, 1)),
        "encoder.mid_block.attentions.0.query.weight": np.zeros((4, 4)),
        "decoder.mid_block.resnets.0.conv2.weight": np.zeros((4, 4, 3, 3)),
    }
    out = convert_vae_state_dict(vae)
    assert "encoder.down.0.block.0.conv1.weight" in out
    assert "encoder.down.0.downsample.conv.weight" in out
    assert "decoder.up.2.block.2.nin_shortcut.weight" in out
    assert "encoder.mid.attn_1.q.weight" in out
    # mid-attention linears are reshaped to 1x1 convs
    assert out["encoder.mid.attn_1.q.weight"].shape == (4, 4, 1, 1)
    assert "decoder.mid.block_1.conv2.weight" in out

    full = diffusers_to_original(unet, vae, {"embeddings.x": np.zeros((2,))})
    assert "model.diffusion_model.time_embed.0.weight" in full
    assert "first_stage_model.encoder.down.0.block.0.conv1.weight" in full
    assert "cond_stage_model.transformer.embeddings.x" in full


def test_megatron_bert_export_round_trip():
    """fs→HF export (params_to_torch_state): torch MegatronBert loads
    the exported state dict and reproduces our logits."""
    torch = pytest.importorskip("torch")
    import transformers

    from fengshen_tpu.models.megatron_bert import (
        MegatronBertConfig, MegatronBertForMaskedLM)
    from fengshen_tpu.models.megatron_bert.convert import (
        params_to_torch_state, torch_to_params)

    cfg = MegatronBertConfig(
        vocab_size=120, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, dtype="float32",
        param_dtype="float32", scan_layers=True)
    model = MegatronBertForMaskedLM(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    state = {k: torch.tensor(np.ascontiguousarray(v)) for k, v in
             params_to_torch_state(params, cfg).items()}

    hf_cfg = transformers.MegatronBertConfig(
        vocab_size=120, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64)
    tm = transformers.MegatronBertForMaskedLM(hf_cfg).eval()
    missing, unexpected = tm.load_state_dict(state, strict=False)
    # everything torch NEEDS must be provided
    assert not [m for m in missing if "position_ids" not in m], missing

    ids = np.array([[2, 17, 9, 42, 7, 99, 1, 5]], np.int64)
    with torch.no_grad():
        ref = tm(torch.tensor(ids)).logits.numpy()
    ours = model.apply({"params": params}, jnp.asarray(ids, jnp.int32))
    np.testing.assert_allclose(np.asarray(ours), ref, atol=2e-3)

    # and the import of the export is the identity
    back = torch_to_params(state, cfg, head="masked_lm")
    flat_a = jax.tree_util.tree_leaves(params)
    flat_b = jax.tree_util.tree_leaves(back)
    assert len(flat_a) == len(flat_b)
    for a, b in zip(flat_a, flat_b):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6)


def test_t5_export_round_trip():
    """fs→HF export for the Randeng/T5 family: torch loads the export
    and reproduces our logits; re-import is the identity."""
    torch = pytest.importorskip("torch")
    import transformers

    from fengshen_tpu.models.t5 import T5Config, T5ForConditionalGeneration
    from fengshen_tpu.models.t5.convert import (params_to_torch_state,
                                                torch_to_params)

    cfg = T5Config(vocab_size=120, d_model=32, d_kv=8, d_ff=64,
                   num_layers=2, num_heads=4, dtype="float32",
                   tie_word_embeddings=False)
    model = T5ForConditionalGeneration(cfg)
    ids = jnp.zeros((1, 8), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids, ids)["params"]
    state = {k: torch.tensor(np.ascontiguousarray(v)) for k, v in
             params_to_torch_state(params, cfg).items()}

    hf_cfg = transformers.T5Config(
        vocab_size=120, d_model=32, d_kv=8, d_ff=64, num_layers=2,
        num_heads=4, feed_forward_proj="relu",
        tie_word_embeddings=False)
    tm = transformers.T5ForConditionalGeneration(hf_cfg).eval()
    missing, _ = tm.load_state_dict(state, strict=False)
    assert not missing, missing

    enc = np.array([[2, 17, 9, 42, 7, 99, 1, 5]], np.int64)
    dec = np.array([[0, 3, 8, 21]], np.int64)
    with torch.no_grad():
        ref = tm(input_ids=torch.tensor(enc),
                 decoder_input_ids=torch.tensor(dec)).logits.numpy()
    ours = model.apply({"params": params}, jnp.asarray(enc, jnp.int32),
                       jnp.asarray(dec, jnp.int32))
    np.testing.assert_allclose(np.asarray(ours), ref, atol=2e-3)

    back = torch_to_params(state, cfg)
    for a, b in zip(jax.tree_util.tree_leaves(params),
                    jax.tree_util.tree_leaves(back)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6)
