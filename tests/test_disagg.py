"""Prefill/decode disaggregation (ISSUE 13, docs/disaggregation.md):
KV-handoff subsystem + phase-aware fleet placement.

Four tiers:

- UNIT tests over the router-process half (`disagg/policy.py`,
  `disagg/transfer.py`): phase validation, least-occupied pair
  planning with every degenerate topology, topology labels, checksum
  seal/tamper, and the push adopt-ack contract (exact `KvPushError`
  reason + `sent` per failure mode) — no jax, no sockets;
- ENGINE tests over `serving/handoff.py` on a tiny llama: THE
  acceptance pin — greedy outputs token-identical to a single-engine
  baseline through a REAL export→adopt→detach handoff, across slot AND
  paged layouts and the int8-for-transfer → fp32-decode path, with the
  engines' compile counts pinned (handoff adds ZERO jitted programs) —
  plus the adopt-decline reason matrix and export/detach edge cases;
- HTTP tests over two REAL stdlib replicas (prefill + decode phases,
  each with its `DisaggCoordinator`) behind the REAL `FleetRouter`:
  phase-aware placement pushes the lane, the router collects the
  redirect, bodies are token-identical and the assembled trace shows
  the handoff on BOTH replicas — and the degradation pin: kill / wedge
  / adopt-decline faults at exact KV-push indices all degrade to local
  decode with zero client errors, token-identical results, and
  `fstpu_disagg_fallbacks_total{reason}` matching the faults EXACTLY;
- a pure-stdlib SUBPROCESS pin: the policy+transfer half the router
  imports must never pull jax.
"""

import json
import os
import subprocess
import sys
import threading
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from fengshen_tpu.disagg import (KvPushError, plan_handoff,
                                 push_payload, seal, topology,
                                 validate_phase, verify_checksum)
from fengshen_tpu.disagg.coordinator import DisaggCoordinator
from fengshen_tpu.fleet import (FleetConfig, FleetFaultPlan,
                                FleetRouter, TransportError,
                                UrllibTransport)
from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from fengshen_tpu.serving import (ContinuousBatchingEngine,
                                  EngineConfig, handoff)
from fengshen_tpu.utils.generate import generate

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

PAGED = dict(kv_layout="paged", kv_block_size=8, kv_num_blocks=17)


# ---- unit tier: policy --------------------------------------------------

class _Rep:
    def __init__(self, name, phase, occ=0.0):
        self.name = name
        self.phase = phase
        self._occ = occ

    def occupancy(self):
        return self._occ


def test_validate_phase():
    assert validate_phase("prefill") == "prefill"
    assert validate_phase(" Decode ") == "decode"
    assert validate_phase("") == "both"
    assert validate_phase(None) == "both"
    with pytest.raises(ValueError):
        validate_phase("prefil")


def test_plan_handoff_needs_both_dedicated_tiers():
    """Every degenerate topology plans None — disaggregation never
    becomes a new way to fail a request."""
    assert plan_handoff([]) is None
    assert plan_handoff([_Rep("a", "both"), _Rep("b", "both")]) is None
    assert plan_handoff([_Rep("a", "prefill"),
                         _Rep("b", "both")]) is None
    assert plan_handoff([_Rep("a", "decode"),
                         _Rep("b", "decode")]) is None
    plan = plan_handoff([_Rep("a", "prefill"), _Rep("b", "decode"),
                         _Rep("c", "both")])
    assert (plan.prefill.name, plan.decode.name) == ("a", "b")


def test_plan_handoff_picks_least_occupied_per_tier():
    reps = [_Rep("p0", "prefill", 0.5), _Rep("p1", "prefill", 0.25),
            _Rep("d0", "decode", 0.75), _Rep("d1", "decode", 0.25),
            _Rep("d2", "decode", 0.25)]
    plan = plan_handoff(reps)
    assert plan.prefill.name == "p1"
    assert plan.decode.name == "d1"      # tie → iteration order


def test_topology_labels():
    assert topology([]) == "homogeneous"
    assert topology(["both", "both", "both"]) == "homogeneous"
    assert topology(["prefill", "decode"]) == "prefill=1,decode=1"
    assert topology(["prefill", "prefill", "decode", "both"]) == \
        "prefill=2,decode=1,both=1"


# ---- unit tier: transfer ------------------------------------------------

def test_seal_and_checksum_tamper():
    payload = seal({"kind": "fstpu-kv-handoff", "request_id": "r-1",
                    "tokens": [1, 2, 3]})
    assert verify_checksum(payload)
    assert not verify_checksum(dict(payload, tokens=[1, 2, 4]))
    assert not verify_checksum({"tokens": [1, 2, 3]})
    # the checksum field itself is excluded from the hashed bytes
    assert seal(dict(payload))["checksum"] == payload["checksum"]


class _AckTransport:
    """Scripted peer for the push adopt-ack contract."""

    def __init__(self, status=200, body=None, exc=None):
        self.status, self.body, self.exc = status, body, exc
        self.calls = []

    def request(self, base_url, method, path, body, timeout_s):
        self.calls.append((base_url, method, path))
        if self.exc is not None:
            raise self.exc
        return self.status, self.body


def _push(t, **kw):
    payload = seal({"request_id": "r-1", "tokens": [1, 2]})
    return push_payload("http://d:1", "r-1", payload, transport=t, **kw)


def test_push_ack_contract():
    """200 + {"adopted": true} is the ONLY success; every failure mode
    maps to ONE KvPushError with the exact reason+sent the fallback
    counter labels."""
    ok = _AckTransport(200, {"adopted": True, "request_id": "r-1"})
    assert _push(ok)["adopted"] is True
    assert ok.calls == [("http://d:1", "PUT", "/kv/r-1")]

    with pytest.raises(KvPushError) as e:
        _push(_AckTransport(409, {"adopted": False, "reason": "shape"}))
    assert (e.value.reason, e.value.sent) == ("adopt_declined", True)

    # a well-formed decline is adopt_declined even on status 200
    with pytest.raises(KvPushError) as e:
        _push(_AckTransport(200, {"adopted": False, "reason": "x"}))
    assert e.value.reason == "adopt_declined"

    with pytest.raises(KvPushError) as e:
        _push(_AckTransport(500, {"error": "boom"}))
    assert (e.value.reason, e.value.sent) == ("http_500", True)

    with pytest.raises(KvPushError) as e:
        _push(_AckTransport(exc=TransportError("dead", sent=False)))
    assert (e.value.reason, e.value.sent) == ("connect", False)

    with pytest.raises(KvPushError) as e:
        _push(_AckTransport(exc=TransportError("hung", sent=True)))
    assert (e.value.reason, e.value.sent) == ("timeout", True)

    # the size cap trips BEFORE anything leaves the process
    capped = _AckTransport(200, {"adopted": True})
    with pytest.raises(KvPushError) as e:
        _push(capped, max_bytes=8)
    assert (e.value.reason, e.value.sent) == ("too_large", False)
    assert capped.calls == []


def test_disagg_router_half_is_jax_free(tmp_path):
    """The policy+transfer half rides in the fleet router process: the
    no-jax contract pinned on `fengshen_tpu.fleet` extends to
    `fengshen_tpu.disagg` (its __init__ and everything it imports)."""
    script = """
import sys
assert "jax" not in sys.modules
import fengshen_tpu.disagg as d
from fengshen_tpu.disagg import plan_handoff, seal, topology
assert "jax" not in sys.modules, "disagg router half must stay jax-free"

class R:
    def __init__(self, phase): self.phase = phase
    def occupancy(self): return 0.0

plan = plan_handoff([R("prefill"), R("decode")])
assert plan is not None
assert topology(["prefill", "decode"]) == "prefill=1,decode=1"
assert "checksum" in seal({"tokens": [1]})
print("ok")
"""
    out = subprocess.run([sys.executable, "-c", script],
                         capture_output=True, text=True, cwd=REPO)
    assert out.returncode == 0, out.stderr
    assert out.stdout.strip() == "ok"


# ---- engine tier: real handoff on a tiny llama --------------------------

@pytest.fixture(scope="module")
def tiny():
    cfg = LlamaConfig(vocab_size=97, hidden_size=32,
                      intermediate_size=64, num_hidden_layers=2,
                      num_attention_heads=4,
                      max_position_embeddings=64, dtype="float32")
    model = LlamaForCausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 4), jnp.int32))["params"]
    return model, params


class _IntTok:
    eos_token_id = None
    pad_token_id = 0

    def encode(self, text):
        return [int(t) for t in text.split()]

    def decode(self, ids):
        return " ".join(str(int(t)) for t in ids)


def _ref(model, params, prompt, max_new):
    out = np.asarray(generate(model, params, jnp.asarray(prompt)[None],
                              max_new_tokens=max_new))
    return out[0, len(prompt):].tolist()


_PROMPT = np.random.RandomState(0).randint(3, 96, 6).astype(np.int32)
_MAX_NEW = 12


def _mk_engine(tiny, **kw):
    model, params = tiny
    kw = dict({"num_slots": 2}, **kw)
    return ContinuousBatchingEngine(
        model, params,
        EngineConfig(buckets=(8,), max_new_tokens=_MAX_NEW,
                     pad_token_id=0, **kw))


def _prime(engine, ticks=4):
    """Submit the shared prompt and tick until mid-decode."""
    req = engine.submit(_PROMPT)
    engine.step()                       # admit + prefill + first token
    for _ in range(ticks):
        engine.step()
    assert req.state == "running"
    return req


@pytest.mark.parametrize("name,src_kw,dst_kw", [
    ("fp32slot->fp32slot", {}, {}),
    ("fp32slot->fp32paged", {}, PAGED),
    ("int8paged->fp32slot", dict(kv_dtype="int8", **PAGED), {}),
    ("int8slot->int8paged", dict(kv_dtype="int8"),
     dict(kv_dtype="int8", **PAGED)),
])
def test_handoff_token_identity(tiny, name, src_kw, dst_kw):
    """THE acceptance pin: a request primed on one engine, exported
    mid-decode, adopted by a second engine and decoded to completion
    produces tokens IDENTICAL to the single-engine fp32 baseline —
    across slot AND paged layouts on both ends, including the
    int8-for-transfer → fp32-decode path (the wire is always int8; on
    this fixture the per-(token, head) scales reproduce fp32 greedy
    exactly, and int8→int8 re-places the wire bits verbatim)."""
    model, params = tiny
    src = _mk_engine(tiny, **src_kw)
    dst = _mk_engine(tiny, **dst_kw)
    req = _prime(src)
    payload = handoff.export_lane(src, req.request_id)
    # int8-for-transfer even off an fp32 tier: the KV prefix rides
    # quantized with per-(token, head) scales
    assert payload["wire_dtype"] == "int8"
    assert all(layer["k"]["dtype"] == "int8"
               for layer in payload["layers"])
    assert verify_checksum(payload)
    adopted = handoff.adopt_lane(dst, payload)
    assert handoff.detach_lane(src, req.request_id, target="peer")
    assert req.state == "handed_off"
    dst.run_until_idle()
    assert adopted.state == "finished"
    assert adopted.tokens == _ref(model, params, _PROMPT, _MAX_NEW), name


def test_handoff_adds_zero_jitted_programs(tiny):
    """Export is an eager gather and adopt an eager scatter: after a
    full handoff the source holds exactly its pinned program set (one
    decode, one prefill bucket, one assign) and the receiver — which
    never ran a prefill — holds ONE decode program and nothing else."""
    src = _mk_engine(tiny)
    dst = _mk_engine(tiny)
    if not hasattr(src._decode_jit, "_cache_size"):
        pytest.skip("jit cache introspection unavailable")
    req = _prime(src)
    payload = handoff.export_lane(src, req.request_id)
    adopted = handoff.adopt_lane(dst, payload)
    assert handoff.detach_lane(src, req.request_id, target="peer")
    dst.run_until_idle()
    assert adopted.state == "finished"
    assert src._decode_jit._cache_size() == 1
    assert src._prefill_jit._cache_size() == 1   # one per bucket
    assert src._assign_jit._cache_size() == 1
    assert dst._decode_jit._cache_size() == 1
    assert dst._prefill_jit._cache_size() == 0   # adopt never prefills
    assert dst._assign_jit._cache_size() == 0


def test_adopt_decline_reasons(tiny):
    """The header-validation matrix: each corruption declines with ITS
    exact reason (the label the source's fallback counter carries) and
    leaves the receiving engine untouched."""
    src = _mk_engine(tiny)
    dst = _mk_engine(tiny)
    req = _prime(src)
    payload = handoff.export_lane(src, req.request_id)

    def decline(p):
        before = dst.stats()["slots_active"]
        with pytest.raises(handoff.AdoptDecline) as e:
            handoff.adopt_lane(dst, p)
        assert dst.stats()["slots_active"] == before
        return e.value.reason

    assert decline(seal(dict(payload, version=99))) == "version"
    assert decline(dict(payload, pos=payload["pos"] + 1)) == "checksum"
    assert decline(seal(dict(payload, model_fingerprint="other"))) == \
        "model_fingerprint"
    controls = dict(payload["controls"], pad_token_id=7)
    assert decline(seal(dict(payload, controls=controls))) == \
        "controls"

    # a clean adopt succeeds once; the same request id again declines
    adopted = handoff.adopt_lane(dst, payload)
    assert decline(dict(payload)) == "duplicate_request_id"
    dst.run_until_idle()
    assert adopted.state == "finished"

    # a full engine declines with "no_free_slot" (header valid)
    full = _mk_engine(tiny, num_slots=1)
    _prime(full, ticks=1)
    with pytest.raises(handoff.AdoptDecline) as e:
        handoff.adopt_lane(full, payload)
    assert e.value.reason == "no_free_slot"


def test_export_and_detach_edges(tiny):
    """Export refuses unknown / not-yet-running / finished lanes with
    HandoffError; detach after a local finish returns False (the local
    result stands — the coordinator cancels the adopted twin)."""
    eng = _mk_engine(tiny)
    with pytest.raises(handoff.HandoffError):
        handoff.export_lane(eng, "nope")
    req = eng.submit(_PROMPT)            # queued, never ticked
    with pytest.raises(handoff.HandoffError):
        handoff.export_lane(eng, req.request_id)
    eng.run_until_idle()
    assert req.state == "finished"
    with pytest.raises(handoff.HandoffError):
        handoff.export_lane(eng, req.request_id)
    assert handoff.detach_lane(eng, req.request_id) is False


# ---- HTTP tier: real replicas, real router ------------------------------

def _start_phase_replica(tiny, phase, max_new, transport=None,
                         tick_delay_s=0.0):
    """One real stdlib replica with a disagg coordinator. Returns
    (server, engine, coordinator). `tick_delay_s` throttles the decode
    tick (the `_decode_jit` wrap idiom from the debug tests): the tiny
    model otherwise finishes a whole generation faster than the
    coordinator's prime-poll can observe it RUNNING — a pace no real
    model reaches — which would race every handoff into local_finish."""
    import time as _time

    from fengshen_tpu.api.main import (PipelineConfig, ServerConfig,
                                       build_stdlib_server)
    from fengshen_tpu.pipelines.text_generation import Pipeline
    model, params = tiny
    pipe = Pipeline(module=model, params=params, tokenizer=_IntTok(),
                    max_new_tokens=max_new, eos_token_id=None,
                    pad_token_id=0)
    engine = ContinuousBatchingEngine(
        model, params,
        EngineConfig(num_slots=2, buckets=(8,), max_new_tokens=max_new,
                     max_queue=32, pad_token_id=0))
    engine.warmup()
    if tick_delay_s:
        real = engine._decode_jit

        def slow_decode(*a, **kw):
            _time.sleep(tick_delay_s)
            return real(*a, **kw)

        engine._decode_jit = slow_decode
    engine.start()
    coord = DisaggCoordinator(engine, pipe, transport=transport)
    ready = threading.Event()
    ready.set()
    server = build_stdlib_server(
        ServerConfig(host="127.0.0.1", port=0, engine="continuous",
                     phase=phase),
        PipelineConfig(task="text_generation"), pipeline=pipe,
        engine=engine, ready=ready, draining=threading.Event(),
        disagg=coord)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    return server, engine, coord


def _labelled(counter):
    return {k[0]: int(c.value) for k, c in counter.children()
            if c.value}


def _events(base, rid):
    with urllib.request.urlopen(
            f"http://{base}/debug/requests/{rid}", timeout=10) as r:
        wf = json.loads(r.read())
    return [e["event"] for e in wf["events"]]


def test_disagg_http_end_to_end_token_identical(tiny):
    """Phase-aware placement over two REAL replicas: admissions land on
    the prefill tier, the primed lane is pushed to the decode tier, the
    router collects the redirect — every response is 200,
    token-identical to the single-engine baseline, and the assembled
    trace shows the handoff on BOTH replicas' waterfalls."""
    model, params = tiny
    max_new = 32
    fleet = [_start_phase_replica(
        tiny, phase, max_new,
        tick_delay_s=0.03 if phase == "prefill" else 0.0)
             for phase in ("prefill", "decode")]
    targets = [f"127.0.0.1:{s.server_address[1]}"
               for s, *_ in fleet]
    router = FleetRouter(
        FleetConfig(replicas=targets, recovery_probes=1,
                    backoff_base_s=0.0, request_timeout_s=60.0),
        transport=UrllibTransport(), sleep=lambda s: None)
    try:
        router.poll_once()
        assert router.healthy_count() == 2
        state = router.fleet_state()
        assert state["topology"] == "prefill=1,decode=1"
        rng = np.random.RandomState(1)
        prompts = [rng.randint(3, 96, n).astype(np.int32)
                   for n in (4, 6, 7)]
        bodies = []
        for p in prompts:
            code, body = router.route_generate(
                {"input_text": " ".join(str(t) for t in p)})
            assert code == 200, body
            bodies.append(body)
        refs = [" ".join(str(t) for t in _ref(model, params, p,
                                              max_new))
                for p in prompts]
        assert [b["result"] for b in bodies] == refs
        # every request went through a REAL handoff (collected from the
        # decode replica, not answered locally)
        assert all(b.get("adopted") is True for b in bodies)
        pre_coord, dec_coord = fleet[0][2], fleet[1][2]
        assert _labelled(pre_coord.registry.get(
            "fstpu_disagg_handoffs_total")) == {"redirected": 3}
        assert int(dec_coord.registry.get(
            "fstpu_disagg_adopted_total").value()) == 3
        assert dec_coord.adopted_count() == 0   # all collected
        # the assembled trace stitches BOTH replicas: the prefill
        # waterfall ends in the handoff, the decode one starts with
        # the adoption
        assembled = router.assemble(bodies[-1]["trace_id"])
        assert sorted(assembled["replicas"]) == sorted(targets)
        pre_wf = assembled["replicas"][targets[0]]["waterfall"]
        dec_wf = assembled["replicas"][targets[1]]["waterfall"]
        assert pre_wf["request_id"] == dec_wf["request_id"] == \
            bodies[-1]["request_id"]
        pre_ev = [e["event"] for e in pre_wf["events"]]
        dec_ev = [e["event"] for e in dec_wf["events"]]
        assert "handoff_export" in pre_ev and "handed_off" in pre_ev
        assert "adopted" in dec_ev and "finished" in dec_ev
    finally:
        for server, engine, _ in fleet:
            server.shutdown()
            server.server_close()
            engine.stop()


def test_disagg_handoff_faults_degrade_to_local(tiny):
    """THE degradation pin (ISSUE 13): kill, wedge, and adopt-decline
    faults at exact KV-push indices — every request still answers 200
    token-identical (local prefill-and-decode absorbed the failure,
    NEVER a client error), `fstpu_disagg_fallbacks_total{reason}`
    matches the injected faults EXACTLY, the wedge's adopted twin is
    cancelled, and the fallback is visible on the request's trace."""
    model, params = tiny
    max_new = 32
    plan = None                          # bound after ports are known
    holder = {}

    class _Lazy:
        """Defers to the fault-wrapped transport once built — the
        coordinators need a transport before the plan exists."""

        def request(self, *a, **kw):
            return holder["t"].request(*a, **kw)

    fleet = [_start_phase_replica(
        tiny, phase, max_new, transport=_Lazy(),
        tick_delay_s=0.03 if phase == "prefill" else 0.0)
             for phase in ("prefill", "decode")]
    targets = [f"127.0.0.1:{s.server_address[1]}"
               for s, *_ in fleet]
    plan = FleetFaultPlan(kv_kill_at={0: targets[1]},
                          kv_wedge_at={1: targets[1]},
                          kv_decline_at={2: targets[1]})
    transport = holder["t"] = plan.wrap(UrllibTransport())
    router = FleetRouter(
        FleetConfig(replicas=targets, recovery_probes=1,
                    backoff_base_s=0.0, request_timeout_s=60.0),
        transport=transport, sleep=lambda s: None)
    transport.bind(router)
    try:
        router.poll_once()
        assert router.healthy_count() == 2
        rng = np.random.RandomState(2)
        prompts = [rng.randint(3, 96, n).astype(np.int32)
                   for n in (5, 4, 6, 7)]
        bodies = []
        for p in prompts:
            code, body = router.route_generate(
                {"input_text": " ".join(str(t) for t in p)})
            assert code == 200, body     # zero client errors, ever
            bodies.append(body)
        refs = [" ".join(str(t) for t in _ref(model, params, p,
                                              max_new))
                for p in prompts]
        assert [b["result"] for b in bodies] == refs
        # the three faulted pushes answered locally; the fourth
        # redirected through the decode tier
        assert [b.get("adopted") for b in bodies] == \
            [None, None, None, True]
        assert plan.fired == [("kv_kill", 0, targets[1]),
                              ("kv_wedge", 1, targets[1]),
                              ("kv_decline", 2, targets[1])]
        # fallbacks counted per reason, matching the faults EXACTLY
        pre_coord, dec_coord = fleet[0][2], fleet[1][2]
        assert _labelled(pre_coord.registry.get(
            "fstpu_disagg_fallbacks_total")) == \
            {"connect": 1, "timeout": 1, "adopt_declined": 1}
        assert _labelled(pre_coord.registry.get(
            "fstpu_disagg_handoffs_total")) == \
            {"fallback": 3, "redirected": 1}
        # the wedge DELIVERED its adopt (plus the clean redirect), and
        # both twins are gone: cancelled on fallback, collected on
        # success — a request never decodes twice to completion
        assert int(dec_coord.registry.get(
            "fstpu_disagg_adopted_total").value()) == 2
        assert dec_coord.adopted_count() == 0
        # no router-level retries: handoff failure is the replica's to
        # absorb, invisible to rotation
        assert router.retries_total() == {}
        # the fallback is on the request's own trace: the prefill
        # replica's waterfall carries the handoff_fallback mark
        ev = _events(targets[0], bodies[0]["request_id"])
        assert "handoff_fallback" in ev and "finished" in ev
        ev_ok = _events(targets[0], bodies[3]["request_id"])
        assert "handed_off" in ev_ok
    finally:
        for server, engine, _ in fleet:
            server.shutdown()
            server.server_close()
            engine.stop()
