"""`make aot-bench` harness guard: the cold-vs-warm AOT bench must emit
its one BENCH-schema JSON line (aot_cold_s, aot_warm_s, speedup,
token_identical) with tiny env shapes, so future BENCH rounds can track
the cold-start win.

The ≥2x acceptance number comes from the DEFAULT (8-layer,
3-bucket) shape, whose two child processes are too slow for the fast
lane — the smoke pins the harness (schema, subprocess plumbing, token
identity); the slow test pins the bar.
"""

import io
import json
import os
from contextlib import redirect_stdout

import pytest

TINY = {"AOT_BENCH_VOCAB": "128", "AOT_BENCH_HIDDEN": "32",
        "AOT_BENCH_INTER": "64", "AOT_BENCH_LAYERS": "2",
        "AOT_BENCH_HEADS": "4", "AOT_BENCH_SLOTS": "2",
        "AOT_BENCH_BUCKETS": "16", "AOT_BENCH_NEW_TOKENS": "4"}


def _run(monkeypatch, env: dict, tiny: bool = True) -> dict:
    from fengshen_tpu.aot import bench

    for key in list(os.environ):
        if key.startswith(("AOT_BENCH_", "BENCH_DEGRADED")):
            monkeypatch.delenv(key)
    for key, val in {**(TINY if tiny else {}), **env}.items():
        monkeypatch.setenv(key, val)
    out = io.StringIO()
    with redirect_stdout(out):
        bench.main()
    lines = [l for l in out.getvalue().splitlines() if l.startswith("{")]
    assert lines, out.getvalue()
    return json.loads(lines[-1])


def test_aot_bench_emits_schema_row(monkeypatch):
    row = _run(monkeypatch, {})
    assert set(row) >= {"metric", "value", "unit", "vs_baseline",
                        "aot_cold_s", "aot_warm_s", "token_identical"}
    assert row["metric"] == "aot_warm_warmup_speedup"
    assert row["unit"] == "x"
    assert row["value"] > 0 and row["value"] == row["vs_baseline"]
    assert row["aot_cold_s"] > 0 and row["aot_warm_s"] > 0
    assert row["token_identical"] is True
    assert row["cache_files"] >= 2   # 1 bucket prefill + decode (+assign)
    assert "degraded" not in row


def test_aot_bench_degraded_flag(monkeypatch):
    row = _run(monkeypatch, {"BENCH_DEGRADED": "1"})
    assert row["degraded"] is True


@pytest.mark.slow
def test_aot_bench_default_shape_warm_2x(monkeypatch):
    """The acceptance bar (ISSUE 5): warm-cache process startup (engine
    warmup incl. all buckets + decode) ≥2x faster than cold-cache on
    this env's CPU backend, with token-identical greedy outputs. Slow
    lane (~25s: two subprocess jax startups at the default shape)."""
    row = _run(monkeypatch, {}, tiny=False)
    assert row["token_identical"] is True, row
    assert row["value"] >= 2.0, row
