"""Observability subsystem tests (fast CPU lane — NOT marked slow):
registry determinism, histogram percentiles vs the reference
implementation, span nesting + the no-profiler fallback, the MFU
estimator against a hand-computed llama-shape FLOPs count, Prometheus
exposition through both server paths, process_index gating, and the
acceptance-bar Trainer fit logging a finite `mfu`.
"""

import argparse
import json
import os
import subprocess
import sys
import textwrap
import threading
import urllib.request

import numpy as np
import pytest

from fengshen_tpu.observability import (JsonlSink, MetricsRegistry,
                                        NOMINAL_FALLBACK_FLOPS, PEAK_FLOPS,
                                        StepStats, current_span_stack,
                                        estimate_flops_per_token,
                                        get_registry, peak_flops_per_chip,
                                        percentile, render_prometheus,
                                        span, start_metrics_server)


# -- registry -------------------------------------------------------------

def test_counter_gauge_histogram_basics():
    r = MetricsRegistry()
    c = r.counter("t_total", "c")
    c.inc()
    c.inc(2)
    assert c.value() == 3
    with pytest.raises(ValueError, match="only go up"):
        c.inc(-1)
    g = r.gauge("t_gauge", "g")
    g.set(5.0)
    g.inc()
    g.dec(0.5)
    assert g.value() == 5.5
    h = r.histogram("t_hist", "h", buckets=(1.0, 10.0))
    for v in (0.5, 2.0, 50.0):
        h.observe(v)
    child = h.labels() if h.labelnames else h._only_child()
    assert child.count == 3 and child.sum == 52.5
    assert child.counts == [1, 1, 1]  # <=1, <=10, +Inf


def test_registry_get_or_create_and_conflicts():
    r = MetricsRegistry()
    a = r.counter("same_total", "x")
    assert r.counter("same_total", "x") is a
    with pytest.raises(ValueError, match="already registered"):
        r.gauge("same_total", "x")
    with pytest.raises(ValueError, match="already registered"):
        r.counter("same_total", "x", labelnames=("k",))
    with pytest.raises(ValueError, match="invalid metric name"):
        r.counter("bad name", "x")
    lab = r.counter("lab_total", "x", labelnames=("k",))
    with pytest.raises(ValueError, match="label"):
        lab.labels("a", "b")
    with pytest.raises(ValueError, match="labelled"):
        lab.inc()


def test_render_prometheus_is_sorted_and_typed():
    r = MetricsRegistry()
    # insert in an order that differs from sorted order
    r.gauge("zz_gauge", "z").set(1)
    c = r.counter("aa_total", "a", labelnames=("k",))
    for key in {"zebra", "alpha", "mid"}:  # set: hash-ordered source
        c.labels(key).inc()
    text = render_prometheus(r)
    lines = text.splitlines()
    assert lines[0] == "# HELP aa_total a"
    assert lines[1] == "# TYPE aa_total counter"
    assert lines[2:5] == ['aa_total{k="alpha"} 1',
                         'aa_total{k="mid"} 1',
                         'aa_total{k="zebra"} 1']
    assert lines[-1] == "zz_gauge 1"


def test_render_deterministic_across_hashseed():
    """Byte-identical exposition no matter PYTHONHASHSEED: label values
    arrive from a set (hash-ordered), rendering must sort them."""
    snippet = textwrap.dedent("""
        from fengshen_tpu.observability import (MetricsRegistry,
                                                render_prometheus)
        r = MetricsRegistry()
        c = r.counter("t_total", "t", labelnames=("k",))
        for key in {"a", "b", "c", "dd", "ee", "zz", "m1", "m2"}:
            c.labels(key).inc()
        h = r.histogram("t_h", "h", labelnames=("k",))
        for key in {"x", "y", "z"}:
            h.labels(key).observe(1.0)
        print(render_prometheus(r))
    """)
    outs = set()
    for seed in ("0", "1", "31337"):
        env = dict(os.environ, PYTHONHASHSEED=seed)
        outs.add(subprocess.run(
            [sys.executable, "-c", snippet], env=env, check=True,
            capture_output=True, text=True).stdout)
    assert len(outs) == 1


def test_histogram_percentile_matches_reference():
    """`registry.percentile` (the single implementation) agrees with
    the PR-3 serving implementation it replaced, across sizes/qs."""
    def reference(values, q):  # verbatim old serving/metrics.py
        vals = sorted(values)
        if not vals:
            return 0.0
        idx = min(int(q * len(vals)), len(vals) - 1)
        return float(vals[idx])

    rng = np.random.RandomState(7)
    r = MetricsRegistry()
    for n in (0, 1, 2, 7, 100, 513):
        h = r.histogram(f"h_{n}", "h", window=512)
        vals = rng.rand(n).tolist()
        for v in vals:
            h.observe(v)
        window = vals[-512:]  # histogram window is bounded
        for q in (0.0, 0.25, 0.5, 0.9, 0.95, 0.99, 1.0):
            assert h.percentile(q) == reference(window, q)
            assert percentile(window, q) == reference(window, q)


# -- spans ----------------------------------------------------------------

def test_span_nesting_and_labels():
    r = MetricsRegistry()
    with span("outer", registry=r):
        assert current_span_stack() == ("outer",)
        with span("inner", registry=r):
            assert current_span_stack() == ("outer", "inner")
    assert current_span_stack() == ()
    metric = r.get("fstpu_span_seconds")
    labels = [v for v, _ in metric.children()]
    assert (("outer",) in labels and ("outer/inner",) in labels)


def test_span_fallback_without_jax_profiler(monkeypatch):
    import fengshen_tpu.observability.tracing as tracing
    monkeypatch.setattr(tracing, "_TRACE_ANNOTATION", None)
    r = MetricsRegistry()
    with span("noprof", registry=r):
        pass
    child = r.get("fstpu_span_seconds").labels("noprof")
    assert child.count == 1 and child.sum >= 0


def test_span_records_on_exception():
    r = MetricsRegistry()
    with pytest.raises(RuntimeError):
        with span("boom", registry=r):
            raise RuntimeError("x")
    assert r.get("fstpu_span_seconds").labels("boom").count == 1
    assert current_span_stack() == ()


# -- flops / mfu ----------------------------------------------------------

class _Cfg:
    def __init__(self, **kw):
        self.__dict__.update(kw)


def test_flops_estimator_hand_computed_llama_shape():
    # h=32, l=3, inter=64, v=97, 4 heads (no GQA):
    #   per_layer = 2*32*32 (q+o) + 2*32*32 (k+v) + 3*32*64 (mlp)
    #             = 2048 + 2048 + 6144 = 10240
    #   total = 3*10240 + 32*97 = 30720 + 3104 = 33824 -> x6 = 202944
    cfg = _Cfg(hidden_size=32, num_hidden_layers=3,
               intermediate_size=64, vocab_size=97,
               num_attention_heads=4)
    assert estimate_flops_per_token(cfg) == 202944.0
    assert estimate_flops_per_token(cfg, include_backward=False) == \
        202944.0 / 3
    # GQA: 8 kv heads of head_dim 128 under 40 query heads (13B shape)
    gqa = _Cfg(hidden_size=5120, num_hidden_layers=1,
               intermediate_size=13824, vocab_size=0,
               num_attention_heads=40, num_key_value_heads=8)
    per_layer = (2 * 5120 * 5120 + 2 * 5120 * (8 * 128)
                 + 3 * 5120 * 13824)
    assert estimate_flops_per_token(gqa) == 6.0 * per_layer
    # unsupported config (no hidden_size/num_hidden_layers) -> None
    assert estimate_flops_per_token(_Cfg(d_model=768)) is None


def test_peak_flops_resolution(monkeypatch):
    assert peak_flops_per_chip("TPU v5e") == PEAK_FLOPS["TPU v5e"]
    assert peak_flops_per_chip("weird chip") == NOMINAL_FALLBACK_FLOPS
    monkeypatch.setenv("FSTPU_PEAK_FLOPS", "2.5e13")
    assert peak_flops_per_chip("TPU v5e") == 2.5e13
    monkeypatch.setenv("FSTPU_PEAK_FLOPS", "-1")
    with pytest.raises(ValueError):
        peak_flops_per_chip()


def test_stepstats_mfu_and_goodput():
    r = MetricsRegistry()
    clock = [0.0]
    stats = StepStats(flops_per_token=100.0, n_devices=2,
                      device_kind="weird chip", registry=r,
                      clock=lambda: clock[0])
    stats.record_execution(n_steps=2, n_tokens=1000)
    clock[0] = 2.0
    entry = stats.window_entry(global_step=2, bad_step_count=0)
    assert entry["tokens_per_sec"] == 500.0
    assert entry["mfu"] == pytest.approx(
        500.0 * 100.0 / (2 * NOMINAL_FALLBACK_FLOPS))
    assert entry["goodput"] == 1.0
    # window resets: no tokens since -> 0 tps
    clock[0] = 3.0
    assert stats.window_entry(4, 0)["tokens_per_sec"] == 0.0
    # guards skipped 3 of 10 steps, one rewind replayed 5
    stats.record_rewind(from_step=10, to_step=5)
    assert stats.goodput(global_step=10, bad_step_count=3) == \
        pytest.approx(7 / 15)
    assert int(r.get("fstpu_train_rewinds_total").value()) == 1


# -- sink -----------------------------------------------------------------

def test_jsonl_sink_writes_and_echoes(tmp_path, capsys):
    path = tmp_path / "sub" / "metrics.jsonl"
    sink = JsonlSink(path=str(path), echo=True)
    sink({"event": "x", "v": 1.23456, "n": 7})
    sink({"event": "y"})
    lines = [json.loads(l) for l in open(path)]
    assert lines == [{"event": "x", "v": 1.23456, "n": 7},
                     {"event": "y"}]
    out = capsys.readouterr().out
    assert "[fengshen-tpu] event=x v=1.235 n=7" in out


def test_jsonl_sink_stream_and_logger(tmp_path):
    import io
    buf = io.StringIO()
    seen = []

    class Logger:
        def log_metrics(self, metrics, step=None):
            seen.append((metrics, step))

    sink = JsonlSink(stream=buf, logger=Logger())
    sink({"step": 3, "loss": 1.5, "note": "text"})
    assert json.loads(buf.getvalue()) == {"step": 3, "loss": 1.5,
                                          "note": "text"}
    assert seen == [({"step": 3, "loss": 1.5}, 3)]


def test_jsonl_sink_process_index_gating(tmp_path, monkeypatch):
    import fengshen_tpu.observability.sink as sink_mod
    monkeypatch.setattr(sink_mod, "_process_index", lambda: 1)
    path = tmp_path / "m.jsonl"
    JsonlSink(path=str(path))({"event": "x"})
    assert not path.exists()
    JsonlSink(path=str(path), only_process_zero=False)({"event": "x"})
    assert path.exists()


# -- exposition endpoints -------------------------------------------------

def _get(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.status, r.headers.get("Content-Type"), \
            r.read().decode()


def test_metrics_exporter_thread_and_gating(monkeypatch):
    reg = MetricsRegistry()
    reg.counter("exp_total", "x").inc(4)
    server = start_metrics_server(0, host="127.0.0.1",
                                  registries=(reg,))
    try:
        code, ctype, body = _get(
            f"http://127.0.0.1:{server.port}/metrics")
        assert code == 200
        assert ctype.startswith("text/plain; version=0.0.4")
        assert "exp_total 4" in body
        code, _, _ = _get(f"http://127.0.0.1:{server.port}/healthz")
        assert code == 200
    finally:
        server.close()
    # multihost gating: non-zero process index binds no socket
    import fengshen_tpu.observability.exposition as expo
    monkeypatch.setattr(expo, "_process_index", lambda: 1)
    assert start_metrics_server(0, registries=(reg,)) is None


def test_metrics_endpoint_stdlib_server_simple_pipeline():
    """GET /metrics on the stdlib server path: valid Prometheus text,
    and the HTTP request counter shows up after a POST."""
    from fengshen_tpu.api.main import (PipelineConfig, ServerConfig,
                                       build_stdlib_server)

    server = build_stdlib_server(
        ServerConfig(host="127.0.0.1", port=0),
        PipelineConfig(task="text_classification"),
        pipeline=lambda text: {"label": 0})
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/api/text_classification",
            data=json.dumps({"input_text": "hi"}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.status == 200
        code, ctype, body = _get(f"http://127.0.0.1:{port}/metrics")
        assert code == 200
        assert ctype.startswith("text/plain; version=0.0.4")
        assert ('fstpu_http_requests_total{route='
                '"/api/text_classification",code="200"} 1') in body
        # every sample line parses as `name{labels} value`
        for line in body.splitlines():
            if line.startswith("#") or not line:
                continue
            name_part, _, value = line.rpartition(" ")
            float(value)
            assert name_part
    finally:
        server.shutdown()


def test_metrics_endpoint_fastapi_path():
    pytest.importorskip("fastapi")
    from fastapi.testclient import TestClient
    from fengshen_tpu.api.main import PipelineConfig, build_app

    app = build_app(PipelineConfig(task="text_classification"),
                    pipeline=lambda text: {"label": 0})
    client = TestClient(app)
    assert client.post("/api/text_classification",
                       json={"input_text": "x"}).status_code == 200
    r = client.get("/metrics")
    assert r.status_code == 200
    assert r.headers["content-type"].startswith(
        "text/plain; version=0.0.4")
    assert "fstpu_http_requests_total" in r.text


# -- engine metrics adapter ----------------------------------------------

def test_engine_metrics_snapshot_shape_pinned():
    """EngineMetrics over the registry keeps the exact PR-3 /stats JSON
    shape, and its registry renders the same numbers as Prometheus."""
    from fengshen_tpu.serving.metrics import EngineMetrics

    m = EngineMetrics()
    m.count("admitted", 2)
    m.count("completed")
    m.record_prefill(64)
    m.record_prefill(64)
    m.record_tick(3, 8, 0.5)
    m.record_ttft(0.2)
    m.record_ttft(0.4)
    m.warmup_compile_s = 1.5
    snap = m.snapshot(queue_depth=1, slots_active=3, num_slots=8,
                      kv={"layout": "paged", "dtype": "int8",
                          "blocks_total": 16, "blocks_used": 5,
                          "blocks_free": 11, "block_tokens": 64,
                          "bytes": 4096, "fragmentation": 0.25})
    assert snap == {
        "queue_depth": 1, "slots_active": 3, "num_slots": 8,
        "admitted": 2, "rejected_queue_full": 0,
        "rejected_prompt_too_long": 0, "rejected_draining": 0,
        "rejected_duplicate": 0,
        "completed": 1,
        "cancelled": 0, "expired": 0,
        "deferred_admissions": 0, "slots_active_peak": 3,
        "kv_layout": "paged", "kv_dtype": "int8",
        "kv_blocks_total": 16, "kv_blocks_used": 5,
        "kv_blocks_free": 11, "kv_block_tokens": 64,
        "kv_cache_bytes": 4096, "kv_fragmentation": 0.25,
        "prefills_per_bucket": {64: 2},
        "decode_ticks": 1, "decode_tokens": 3,
        "decode_tokens_per_sec": 6.0, "slot_occupancy": 0.375,
        "ttft_avg_s": 0.3, "ttft_p50_s": 0.4, "ttft_p95_s": 0.4,
        "warmup_compile_s": 1.5,
        # ISSUE 8: the payload only EXTENDS (uptime + last error type/
        # age — never a traceback); every pre-existing key above is
        # unrenamed
        "uptime_s": 0.0, "last_error": None,
        # ISSUE 10: drain visibility for the fleet router's /stats
        # poll (plus the rejected_draining counter above)
        "draining": False,
    }
    # a spec engine (ISSUE 7) ADDS exactly its five keys — the
    # non-spec payload above stays byte-identical
    assert not any(k.startswith("spec_") for k in snap)
    m.record_spec(8, 5)
    m.record_tick(3, 8, 0.5, tokens=8)   # spec tick: 8 committed
    snap2 = m.snapshot(queue_depth=1, slots_active=3, num_slots=8,
                       kv={"layout": "paged", "dtype": "int8",
                           "blocks_total": 16, "blocks_used": 5,
                           "blocks_free": 11, "block_tokens": 64,
                           "bytes": 4096, "fragmentation": 0.25},
                       spec={"mode": "prompt_lookup", "gamma": 4})
    assert snap2 == dict(snap, decode_ticks=2, decode_tokens=11,
                         decode_tokens_per_sec=11.0,
                         spec_mode="prompt_lookup", spec_gamma=4,
                         spec_drafted_total=8, spec_accepted_total=5,
                         spec_acceptance_rate=0.625)
    text = render_prometheus(m.registry)
    assert "fstpu_serving_admitted_total 2" in text
    assert 'fstpu_serving_prefills_total{bucket="64"} 2' in text
    assert "fstpu_serving_queue_depth 1" in text
    assert "fstpu_kv_blocks_total 16" in text
    assert "fstpu_kv_blocks_used 5" in text
    assert "fstpu_kv_fragmentation 0.25" in text
    assert "fstpu_serving_spec_drafted_total 8" in text
    assert "fstpu_serving_spec_accepted_total 5" in text
    assert "fstpu_spec_accepted_ratio 0.625" in text
    # the kv-less form (bare EngineMetrics) defaults to an empty pool
    assert m.snapshot(1, 3, 8)["kv_blocks_total"] == 0
    # two independent engines never share counts
    m2 = EngineMetrics()
    assert m2.snapshot(0, 0, 8)["admitted"] == 0


# -- trainer integration (the acceptance bar) -----------------------------

def _parse(argv):
    from fengshen_tpu.data.universal_datamodule import UniversalDataModule
    from fengshen_tpu.models.model_utils import add_module_args
    from fengshen_tpu.trainer import add_trainer_args
    parser = argparse.ArgumentParser()
    add_module_args(parser)
    add_trainer_args(parser)
    UniversalDataModule.add_data_specific_args(parser)
    return parser.parse_args(argv)


def test_trainer_fit_logs_finite_mfu_and_goodput(tmp_path):
    """Tiny CPU fit: every step entry carries a finite `mfu` computed
    by the estimator (nominal CPU peak) and a goodput of 1.0 on a
    clean run; the exporter flag serves the same numbers over HTTP."""
    from fengshen_tpu.data import UniversalDataModule
    from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from fengshen_tpu.trainer import Trainer
    from fengshen_tpu.trainer.modules import CausalLMModule

    args = _parse(["--train_batchsize", "4", "--learning_rate", "1e-3",
                   "--warmup_steps", "1", "--log_every_n_steps", "1",
                   "--max_steps", "2", "--metrics_port", "0",
                   "--default_root_dir", str(tmp_path)])
    cfg = LlamaConfig(vocab_size=64, hidden_size=16,
                      intermediate_size=32, num_hidden_layers=1,
                      num_attention_heads=2,
                      max_position_embeddings=32, dtype="float32")
    rng = np.random.RandomState(0)
    rows = [{"input_ids": rng.randint(0, 63, 16).tolist()}
            for _ in range(16)]

    class DS:
        def __len__(self):
            return len(rows)

        def __getitem__(self, i):
            return rows[i]

    module = CausalLMModule(args, LlamaForCausalLM(cfg), cfg)
    dm = UniversalDataModule(args=args, datasets={"train": DS()})
    trainer = Trainer(args)
    state = trainer.fit(module, dm)
    assert int(state.step) == 2

    lines = [json.loads(l)
             for l in open(os.path.join(tmp_path, "metrics.jsonl"))]
    steps = [l for l in lines if "mfu" in l]
    assert len(steps) == 2
    for entry in steps:
        assert np.isfinite(entry["mfu"]) and entry["mfu"] > 0
        assert entry["goodput"] == 1.0
        assert np.isfinite(entry["tokens_per_sec"])
    # the estimator (not 6N) provided flops_per_token: cross-check the
    # published gauge against a recomputation from the entry
    from fengshen_tpu.observability import get_registry
    reg = get_registry()
    assert reg.get("fstpu_train_mfu") is not None
    assert reg.get("fstpu_train_step").value() == 2
    # spans recorded for load/step (checkpoint span needs a ckpt cb)
    span_labels = {v[0] for v, _ in
                   reg.get("fstpu_span_seconds").children()}
    assert "train/load" in span_labels
    assert "train/step" in span_labels
