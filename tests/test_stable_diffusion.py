"""Stable Diffusion component + training-step tests."""

import pytest
import jax
import jax.numpy as jnp
import numpy as np

pytestmark = pytest.mark.slow  # full-fit/e2e lane: run with -m slow or no -m filter


def test_scheduler_add_noise_and_velocity():
    from fengshen_tpu.models.stable_diffusion import DDPMScheduler
    s = DDPMScheduler()
    x = jnp.ones((2, 4, 4, 4))
    eps = jnp.full((2, 4, 4, 4), 0.5)
    t = jnp.asarray([0, 999])
    noisy = s.add_noise(x, eps, t)
    # t=0: almost all signal; t=999: almost all noise
    assert abs(float(noisy[0].mean()) - 1.0) < 0.1
    assert abs(float(noisy[1].mean()) - 0.5) < 0.15
    v = s.get_velocity(x, eps, t)
    assert v.shape == x.shape
    # step() inverts one denoise step finitely
    out = s.step(eps, jnp.asarray(500), noisy[0])
    assert np.isfinite(np.asarray(out)).all()


def test_vae_roundtrip_shapes():
    from fengshen_tpu.models.stable_diffusion import AutoencoderKL
    from fengshen_tpu.models.stable_diffusion.autoencoder_kl import VAEConfig
    cfg = VAEConfig.small_test_config()
    vae = AutoencoderKL(cfg)
    px = jnp.asarray(np.random.RandomState(0).rand(1, 16, 16, 3),
                     jnp.float32)
    params = vae.init(jax.random.PRNGKey(0), px)["params"]
    recon, mean, logvar = vae.apply({"params": params}, px)
    assert mean.shape == (1, 8, 8, 4)       # 1/2 res, 4-ch latents
    assert recon.shape == px.shape
    lat = vae.apply({"params": params}, px, method=AutoencoderKL.encode)
    assert lat[0].shape == (1, 8, 8, 4)


def test_unet_conditional_forward():
    from fengshen_tpu.models.stable_diffusion import UNet2DConditionModel
    from fengshen_tpu.models.stable_diffusion.unet import UNetConfig
    cfg = UNetConfig.small_test_config()
    unet = UNet2DConditionModel(cfg)
    lat = jnp.asarray(np.random.RandomState(0).randn(2, 8, 8, 4),
                      jnp.float32)
    t = jnp.asarray([10, 500])
    text = jnp.asarray(np.random.RandomState(1).randn(2, 5, 32), jnp.float32)
    params = unet.init(jax.random.PRNGKey(0), lat, t, text)["params"]
    out = unet.apply({"params": params}, lat, t, text)
    assert out.shape == (2, 8, 8, 4)
    # conditioning matters: different text changes the output
    out2 = unet.apply({"params": params}, lat, t, text + 1.0)
    assert float(jnp.abs(out - out2).max()) > 1e-6


def test_taiyi_sd_training_step():
    from fengshen_tpu.models.bert import BertConfig
    from fengshen_tpu.models.stable_diffusion import (
        TaiyiStableDiffusion, diffusion_loss)
    from fengshen_tpu.models.stable_diffusion.autoencoder_kl import VAEConfig
    from fengshen_tpu.models.stable_diffusion.unet import UNetConfig

    text_cfg = BertConfig.small_test_config(dtype="float32")
    model = TaiyiStableDiffusion(text_cfg, VAEConfig.small_test_config(),
                                 UNetConfig.small_test_config())
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 127, (2, 6)),
                      jnp.int32)
    px = jnp.asarray(np.random.RandomState(1).rand(2, 16, 16, 3),
                     jnp.float32)
    t = jnp.asarray([3, 700])
    noise = jnp.asarray(np.random.RandomState(2).randn(2, 8, 8, 4),
                        jnp.float32)
    params = model.init(jax.random.PRNGKey(0), ids, px, t, noise)["params"]

    def loss_fn(p):
        pred, latents = model.apply({"params": p}, ids, px, t, noise)
        return diffusion_loss(pred, latents, noise, t)

    loss, grads = jax.value_and_grad(loss_fn)(params)
    assert np.isfinite(float(loss))
    gnorm = sum(float(jnp.abs(g).sum()) for g in
                jax.tree_util.tree_leaves(grads))
    assert gnorm > 0
    # v-prediction switch produces a different, finite loss
    def loss_v(p):
        pred, latents = model.apply({"params": p}, ids, px, t, noise)
        return diffusion_loss(pred, latents, noise, t,
                              prediction_type="v_prediction")
    lv = loss_v(params)
    assert np.isfinite(float(lv)) and abs(float(lv) - float(loss)) > 1e-8
