"""`make serve-bench` harness guard: the serving microbench must emit
its one JSON line (tokens/s, ttft, speedup-vs-sequential) on CPU with
tiny env shapes, so future BENCH rounds can track serving throughput.

The ≥3x-at-8-concurrent acceptance number comes from the DEFAULT
(weight-memory-bound) shape, which is too slow for the fast lane — this
smoke only pins the harness: schema, positivity, degraded flag wiring.
"""

import io
import json
import os
from contextlib import redirect_stdout

import pytest

TINY = {"SERVE_BENCH_SLOTS": "4", "SERVE_BENCH_REQUESTS": "4",
        "SERVE_BENCH_NEW_TOKENS": "8", "SERVE_BENCH_VOCAB": "128",
        "SERVE_BENCH_HIDDEN": "32", "SERVE_BENCH_INTER": "64",
        "SERVE_BENCH_LAYERS": "2", "SERVE_BENCH_HEADS": "4",
        "SERVE_BENCH_BUCKETS": "16,32"}


def _run(monkeypatch, env: dict, tiny: bool = True) -> dict:
    from fengshen_tpu.serving import bench

    for key in list(os.environ):
        if key.startswith(("SERVE_BENCH_", "BENCH_DEGRADED")):
            monkeypatch.delenv(key)
    for key, val in {**(TINY if tiny else {}), **env}.items():
        monkeypatch.setenv(key, val)
    out = io.StringIO()
    with redirect_stdout(out):
        bench.main()
    lines = [l for l in out.getvalue().splitlines() if l.startswith("{")]
    assert lines, out.getvalue()
    return json.loads(lines[-1])


def test_serve_bench_emits_schema_row(monkeypatch):
    row = _run(monkeypatch, {})
    assert set(row) >= {"metric", "value", "unit", "vs_baseline",
                        "sequential_tokens_per_sec", "ttft_avg_s"}
    assert row["metric"] == "serving_engine_tokens_per_sec"
    assert row["unit"] == "tokens/s"
    assert row["value"] > 0
    assert row["sequential_tokens_per_sec"] > 0
    assert row["vs_baseline"] > 0
    assert row["ttft_avg_s"] >= 0
    assert row["requests"] == 4
    assert "degraded" not in row


def test_serve_bench_degraded_flag(monkeypatch):
    row = _run(monkeypatch, {"BENCH_DEGRADED": "1"})
    assert row["degraded"] is True


@pytest.mark.slow
def test_serve_bench_default_shape_beats_sequential_3x(monkeypatch):
    """The acceptance bar (ISSUE 3): ≥3x aggregate tokens/s over
    sequential per-request generate at 8 concurrent requests, on the
    default weight-memory-bound shape. Slow lane (~40s on CPU)."""
    row = _run(monkeypatch, {}, tiny=False)
    assert row["vs_baseline"] >= 3.0, row


PARITY = {"SERVE_BENCH_MODE": "memory_parity",
          "SERVE_BENCH_SLOTS": "2", "SERVE_BENCH_BUCKETS": "8,32",
          "SERVE_BENCH_NEW_TOKENS": "8", "SERVE_BENCH_BLOCK_SIZE": "8"}


def test_serve_bench_memory_parity_schema_and_2x(monkeypatch):
    """Fast-lane guard for `make serve-bench-parity` (ISSUE 6): the
    BENCH schema row, per-variant sections, equal-or-smaller byte
    budgets, and the ≥2x max-concurrent bar — which is DETERMINISTIC
    (admission capacity is allocator math, not timing), so the fast
    lane can assert it on tiny shapes."""
    row = _run(monkeypatch, PARITY)
    assert set(row) >= {"metric", "value", "unit", "vs_baseline",
                        "kv_budget_bytes", "variants",
                        "sequential_tokens_per_sec"}
    assert row["metric"] == "serving_kv_memory_parity_max_concurrent"
    assert row["mode"] == "memory_parity"
    variants = row["variants"]
    assert set(variants) == {"slot", "paged", "paged_int8"}
    budget = row["kv_budget_bytes"]
    for name, v in variants.items():
        assert v["kv_cache_bytes"] <= budget, (name, v)
        assert v["tokens_per_sec"] > 0
        assert v["max_concurrent"] >= 1
    slot_peak = variants["slot"]["max_concurrent"]
    assert variants["paged"]["max_concurrent"] >= 2 * slot_peak, row
    assert variants["paged_int8"]["max_concurrent"] >= \
        2 * slot_peak, row
    assert row["vs_baseline"] >= 2.0


def test_serve_bench_memory_parity_degraded_flag(monkeypatch):
    row = _run(monkeypatch, {**PARITY, "BENCH_DEGRADED": "1"})
    assert row["degraded"] is True


@pytest.mark.slow
def test_serve_bench_memory_parity_acceptance_bar(monkeypatch):
    """ISSUE 6 acceptance: on the weight-memory-bound default shape,
    ≥2x concurrent requests at the same KV byte budget with aggregate
    tokens/s still ≥ the 3x-over-sequential serving bar. Slow lane
    (~4 min on CPU: sequential baseline + three engine warmups)."""
    row = _run(monkeypatch,
               {"SERVE_BENCH_MODE": "memory_parity",
                "SERVE_BENCH_BUCKETS": "32,128",
                "SERVE_BENCH_NEW_TOKENS": "32"}, tiny=False)
    variants = row["variants"]
    slot_peak = variants["slot"]["max_concurrent"]
    for name in ("paged", "paged_int8"):
        assert variants[name]["max_concurrent"] >= 2 * slot_peak, row
        assert variants[name]["vs_sequential"] >= 3.0, row
