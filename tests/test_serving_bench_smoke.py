"""`make serve-bench` harness guard: the serving microbench must emit
its one JSON line (tokens/s, ttft, speedup-vs-sequential) on CPU with
tiny env shapes, so future BENCH rounds can track serving throughput.

The ≥3x-at-8-concurrent acceptance number comes from the DEFAULT
(weight-memory-bound) shape, which is too slow for the fast lane — this
smoke only pins the harness: schema, positivity, degraded flag wiring.
"""

import io
import json
import os
from contextlib import redirect_stdout

import pytest

TINY = {"SERVE_BENCH_SLOTS": "4", "SERVE_BENCH_REQUESTS": "4",
        "SERVE_BENCH_NEW_TOKENS": "8", "SERVE_BENCH_VOCAB": "128",
        "SERVE_BENCH_HIDDEN": "32", "SERVE_BENCH_INTER": "64",
        "SERVE_BENCH_LAYERS": "2", "SERVE_BENCH_HEADS": "4",
        "SERVE_BENCH_BUCKETS": "16,32"}


def _run(monkeypatch, env: dict, tiny: bool = True) -> dict:
    from fengshen_tpu.serving import bench

    for key in list(os.environ):
        if key.startswith(("SERVE_BENCH_", "BENCH_DEGRADED")):
            monkeypatch.delenv(key)
    for key, val in {**(TINY if tiny else {}), **env}.items():
        monkeypatch.setenv(key, val)
    out = io.StringIO()
    with redirect_stdout(out):
        bench.main()
    lines = [l for l in out.getvalue().splitlines() if l.startswith("{")]
    assert lines, out.getvalue()
    return json.loads(lines[-1])


def test_serve_bench_emits_schema_row(monkeypatch):
    row = _run(monkeypatch, {})
    assert set(row) >= {"metric", "value", "unit", "vs_baseline",
                        "sequential_tokens_per_sec", "ttft_avg_s"}
    assert row["metric"] == "serving_engine_tokens_per_sec"
    assert row["unit"] == "tokens/s"
    assert row["value"] > 0
    assert row["sequential_tokens_per_sec"] > 0
    assert row["vs_baseline"] > 0
    assert row["ttft_avg_s"] >= 0
    assert row["requests"] == 4
    assert "degraded" not in row


def test_serve_bench_degraded_flag(monkeypatch):
    row = _run(monkeypatch, {"BENCH_DEGRADED": "1"})
    assert row["degraded"] is True


@pytest.mark.slow
def test_serve_bench_default_shape_beats_sequential_3x(monkeypatch):
    """The acceptance bar (ISSUE 3): ≥3x aggregate tokens/s over
    sequential per-request generate at 8 concurrent requests, on the
    default weight-memory-bound shape. Slow lane (~40s on CPU)."""
    row = _run(monkeypatch, {}, tiny=False)
    assert row["vs_baseline"] >= 3.0, row
