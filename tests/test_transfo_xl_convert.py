"""Transformer-XL importer parity (VERDICT r2 item 3).

Synthetic state dict in the reference naming
(fengshen/models/transfo_xl_denoise/modeling_transfo_xl_denoise.py) vs a
numpy oracle restating the reference equations: fused-qkv relative
attention (:278-340), the pad-reshape `_rel_shift` (:234-249), descending
positional basis (:106-122, :588-591), pre-LN residuals with OpenAI tanh
GELU (:156-162, :455-470), shared r-biases, tied output head (:758-763),
and the XL memory recurrence (:600-660).
"""

import numpy as np
import pytest

H, NH, HD, NL, V = 16, 2, 8, 2, 40


def _sd():
    rng = np.random.RandomState(7)

    def r(*s):
        return rng.randn(*s).astype(np.float32) * 0.1

    sd = {
        "word_embeddings.weight": r(V, H),
        "transformer.r_w_bias": r(NH, HD),
        "transformer.r_r_bias": r(NH, HD),
        "transformer.final_layernorm.weight": 1 + r(H),
        "transformer.final_layernorm.bias": r(H),
    }
    for i in range(NL):
        p = f"transformer.layers.{i}"
        sd.update({
            f"{p}.input_layernorm.weight": 1 + r(H),
            f"{p}.input_layernorm.bias": r(H),
            f"{p}.attention.query_key_value.weight": r(3 * H, H),
            f"{p}.attention.query_key_value.bias": r(3 * H),
            f"{p}.attention.relative.weight": r(H, H),
            f"{p}.attention.relative.bias": r(H),
            f"{p}.attention.dense.weight": r(H, H),
            f"{p}.attention.dense.bias": r(H),
            f"{p}.post_attention_layernorm.weight": 1 + r(H),
            f"{p}.post_attention_layernorm.bias": r(H),
            f"{p}.mlp.dense_h_to_4h.weight": r(4 * H, H),
            f"{p}.mlp.dense_h_to_4h.bias": r(4 * H),
            f"{p}.mlp.dense_4h_to_h.weight": r(H, 4 * H),
            f"{p}.mlp.dense_4h_to_h.bias": r(H),
        })
    return sd


def _ln(x, w, b, eps=1e-5):
    m = x.mean(-1, keepdims=True)
    v = x.var(-1, keepdims=True)
    return (x - m) / np.sqrt(v + eps) * w + b


def _gelu_tanh(x):
    return 0.5 * x * (1.0 + np.tanh(
        0.7978845608028654 * x * (1.0 + 0.044715 * x * x)))


def _pos_emb(klen):
    inv = 1.0 / (10000 ** (np.arange(0, H, 2, dtype=np.float32) / H))
    seq = np.arange(klen - 1, -1, -1, dtype=np.float32)
    ang = seq[:, None] * inv[None]
    return np.concatenate([np.sin(ang), np.cos(ang)], -1)


def _rel_shift(x):
    b, n, q, k = x.shape
    pad = np.zeros((b, n, q, 1), x.dtype)
    xp = np.concatenate([pad, x], -1).reshape(b, n, k + 1, q)
    return xp[:, :, 1:, :].reshape(b, n, q, k)


def _layer(sd, i, x, ltor, pos, mem=None):
    p = f"transformer.layers.{i}"
    ln_x = _ln(x, sd[f"{p}.input_layernorm.weight"],
               sd[f"{p}.input_layernorm.bias"])
    cat = ln_x if mem is None else np.concatenate(
        [_ln(mem, sd[f"{p}.input_layernorm.weight"],
             sd[f"{p}.input_layernorm.bias"]), ln_x], 1)
    B, qlen = x.shape[:2]
    klen = cat.shape[1]
    qkv = cat @ sd[f"{p}.attention.query_key_value.weight"].T + \
        sd[f"{p}.attention.query_key_value.bias"]
    q, k, v = np.split(qkv, 3, -1)
    q = q[:, -qlen:]

    def heads(t):
        return t.reshape(B, t.shape[1], NH, HD).transpose(0, 2, 1, 3)

    q, k, v = heads(q), heads(k), heads(v)
    rel = pos @ sd[f"{p}.attention.relative.weight"].T + \
        sd[f"{p}.attention.relative.bias"]
    rel = rel.reshape(klen, NH, HD).transpose(1, 0, 2)
    r_w = sd["transformer.r_w_bias"]
    r_r = sd["transformer.r_r_bias"]
    ac = np.einsum("bnqd,bnkd->bnqk", q + r_w[None, :, None], k)
    bd = _rel_shift(np.einsum("bnqd,nkd->bnqk",
                              q + r_r[None, :, None], rel))
    scores = (ac + bd) / np.sqrt(HD)
    scores = scores * ltor - 10000.0 * (1.0 - ltor)
    probs = np.exp(scores - scores.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    ctx = np.einsum("bnqk,bnkd->bnqd", probs, v)
    ctx = ctx.transpose(0, 2, 1, 3).reshape(B, qlen, H)
    attn = ctx @ sd[f"{p}.attention.dense.weight"].T + \
        sd[f"{p}.attention.dense.bias"]
    x = x + attn
    y = _ln(x, sd[f"{p}.post_attention_layernorm.weight"],
            sd[f"{p}.post_attention_layernorm.bias"])
    mid = _gelu_tanh(y @ sd[f"{p}.mlp.dense_h_to_4h.weight"].T +
                     sd[f"{p}.mlp.dense_h_to_4h.bias"])
    return x + mid @ sd[f"{p}.mlp.dense_4h_to_h.weight"].T + \
        sd[f"{p}.mlp.dense_4h_to_h.bias"]


def _oracle(sd, ids, mems=None):
    B, qlen = ids.shape
    mem_len = mems[0].shape[1] if mems else 0
    klen = qlen + mem_len
    hidden = sd["word_embeddings.weight"][ids]
    ltor = np.tril(np.ones((qlen, klen), np.float32),
                   k=mem_len)[None, None]
    pos = _pos_emb(klen)
    new_mems = []
    for i in range(NL):
        prev = hidden if mems is None else np.concatenate(
            [mems[i], hidden], 1)
        new_mems.append(prev[:, -8:])
        hidden = _layer(sd, i, hidden, ltor, pos,
                        mems[i] if mems else None)
    hidden = _ln(hidden, sd["transformer.final_layernorm.weight"],
                 sd["transformer.final_layernorm.bias"])
    return hidden @ sd["word_embeddings.weight"].T, new_mems


@pytest.fixture
def ids():
    return np.random.RandomState(3).randint(0, V, (2, 6))


def _config():
    from fengshen_tpu.models.transfo_xl_denoise.modeling_transfo_xl \
        import TransfoXLConfig
    return TransfoXLConfig(vocab_size=V, hidden_size=H, num_layers=NL,
                           num_attention_heads=NH,
                           max_sequence_length=32, max_memory_length=8)


def test_transfo_xl_convert_forward_parity(ids):
    import jax.numpy as jnp

    from fengshen_tpu.models.transfo_xl_denoise.convert import \
        torch_to_params
    from fengshen_tpu.models.transfo_xl_denoise.modeling_transfo_xl \
        import TransfoXLModel

    sd = _sd()
    cfg = _config()
    params = torch_to_params(sd, cfg)["backbone"]
    model = TransfoXLModel(cfg)
    logits, _ = model.apply({"params": params}, jnp.asarray(ids))
    ref, _ = _oracle(sd, ids)
    np.testing.assert_allclose(np.asarray(logits), ref, atol=3e-4)


def test_transfo_xl_memory_recurrence_parity(ids):
    """Segment 2 with XL memory from segment 1 must match the oracle's
    per-layer memory semantics (reference update_mems :649-660)."""
    import jax.numpy as jnp

    from fengshen_tpu.models.transfo_xl_denoise.convert import \
        torch_to_params
    from fengshen_tpu.models.transfo_xl_denoise.modeling_transfo_xl \
        import TransfoXLModel

    sd = _sd()
    cfg = _config()
    params = torch_to_params(sd, cfg)["backbone"]
    model = TransfoXLModel(cfg)
    seg2 = np.random.RandomState(4).randint(0, V, (2, 5))

    _, mems = model.apply({"params": params}, jnp.asarray(ids))
    logits2, _ = model.apply({"params": params}, jnp.asarray(seg2),
                             mems=mems)
    _, ref_mems = _oracle(sd, ids)
    for a, b in zip(mems, ref_mems):
        np.testing.assert_allclose(np.asarray(a), b, atol=3e-4)
    ref2, _ = _oracle(sd, seg2, mems=ref_mems)
    np.testing.assert_allclose(np.asarray(logits2), ref2, atol=5e-4)


def test_transfo_xl_denoise_model_relative_dispatch(ids):
    """TransfoXLDenoiseModel(relative_encoding=True) routes through the
    XL backbone and accepts converted params under 'backbone'."""
    import jax
    import jax.numpy as jnp

    from fengshen_tpu.models.transfo_xl_denoise import (
        TransfoXLDenoiseConfig, TransfoXLDenoiseModel)
    from fengshen_tpu.models.transfo_xl_denoise.convert import \
        torch_to_params

    cfg = TransfoXLDenoiseConfig.small_test_config(
        vocab_size=V, n_embd=H, n_layer=NL, n_head=NH,
        relative_encoding=True, dtype="float32")
    model = TransfoXLDenoiseModel(cfg)
    sd = _sd()
    params = torch_to_params(sd, cfg)
    logits = model.apply({"params": params}, jnp.asarray(ids))
    ref, _ = _oracle(sd, ids)
    np.testing.assert_allclose(np.asarray(logits), ref, atol=3e-4)
    # init produces the same tree the converter fills
    init = model.init(jax.random.PRNGKey(0), jnp.asarray(ids))["params"]
    a = jax.tree_util.tree_map(lambda x: tuple(x.shape), init)
    b = jax.tree_util.tree_map(lambda x: tuple(x.shape), params)
    assert a == b


def test_transfo_xl_denoise_forward_segments_relative(ids):
    """forward_segments in relative mode rides the XL memory (review fix:
    it used to call the cache path and a None lm_head)."""
    import jax
    import jax.numpy as jnp

    from fengshen_tpu.models.transfo_xl_denoise import (
        TransfoXLDenoiseConfig, TransfoXLDenoiseModel)
    from fengshen_tpu.parallel.partition import match_partition_rules

    cfg = TransfoXLDenoiseConfig.small_test_config(
        vocab_size=V, n_embd=H, n_layer=NL, n_head=NH,
        relative_encoding=True, dtype="float32", segment_length=4)
    model = TransfoXLDenoiseModel(cfg)
    long_ids = np.random.RandomState(5).randint(0, V, (2, 8))
    params = model.init(jax.random.PRNGKey(0),
                        jnp.asarray(long_ids[:, :4]))["params"]
    out = model.apply({"params": params}, jnp.asarray(long_ids),
                      method=TransfoXLDenoiseModel.forward_segments)
    assert out.shape == (2, 8, V)
    # segment 2 must see segment 1 through the memory: wrapper __call__
    # with mems must agree with forward_segments' second half
    logits1, mems = model.apply({"params": params},
                                jnp.asarray(long_ids[:, :4]),
                                return_mems=True)
    logits2 = model.apply({"params": params}, jnp.asarray(long_ids[:, 4:]),
                          mems=mems)
    np.testing.assert_allclose(np.asarray(out[:, 4:]),
                               np.asarray(logits2), atol=1e-5)
    # XL partition rules reach every param through the backbone prefix
    specs = match_partition_rules(model.partition_rules(), params)
    flat = jax.tree_util.tree_leaves(
        specs, is_leaf=lambda x: x is None or hasattr(x, "index"))
    assert any(s is not None and any(e for e in s) for s in flat
               if s is not None)


def test_transfo_xl_sharded_matches_replicated(mesh8):
    """XL_PARTITION_RULES shard the relative backbone over fsdp+tensor
    without changing the math (the import path for the published 1.1B
    checkpoints must run sharded on a pod).

    Formerly a non-strict xfail (seed NOTES.md item 4): the fused qkv
    was innocent — the divergence was the `relative` projection's
    contraction dim sharded over the sin|cos positional concat (the
    concat-contraction mispartition, docs/sharding.md "Root cause").
    `relative` is now column-parallel (`relpos` × `heads` logical
    axes); parity is a hard tight-tolerance assertion."""
    import jax
    import jax.numpy as jnp

    from fengshen_tpu.models.transfo_xl_denoise.convert import \
        torch_to_params
    from fengshen_tpu.models.transfo_xl_denoise.modeling_transfo_xl \
        import TransfoXLModel
    from fengshen_tpu.parallel import make_shardings

    sd = _sd()
    cfg = _config()
    params = torch_to_params(sd, cfg)["backbone"]
    params = jax.tree_util.tree_map(jnp.asarray, params)
    model = TransfoXLModel(cfg)
    ids = np.random.RandomState(8).randint(0, V, (4, 8))
    ref, _ = model.apply({"params": params}, jnp.asarray(ids))

    shardings = make_shardings(model.partition_rules(), params, mesh8)
    sharded = jax.device_put(params, shardings)
    # at least the qkv kernels must actually be partitioned
    qkv = sharded["layer_0"]["attention"]["query_key_value"]["kernel"]
    assert any(e is not None for e in qkv.sharding.spec)
    out, _ = jax.jit(
        lambda p, i: model.apply({"params": p}, i))(sharded,
                                                    jnp.asarray(ids))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-4)


def test_transfo_xl_export_echo():
    """fs→reference export (derived inverse): echo of every tensor."""
    from fengshen_tpu.models.transfo_xl_denoise.convert import (
        params_to_torch_state, torch_to_params)

    sd = _sd()
    cfg = _config()
    params = torch_to_params(sd, cfg)
    out = params_to_torch_state(params, cfg, sd)
    assert set(out) == set(sd)
    for k in sd:
        np.testing.assert_array_equal(out[k], sd[k], err_msg=k)
