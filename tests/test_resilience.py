"""Resilience subsystem tests (fast CPU lane — NOT marked slow).

Every behavior is driven by the deterministic fault-injection harness
(`fengshen_tpu.resilience.faults.FaultPlan`): injected NaN losses hit
the in-graph step guard, injected loader faults hit ResilientLoader's
retry/backoff, a real SIGTERM hits the preemption autosave, and a
truncated checkpoint step hits maybe_restore's newest→oldest fallback.
"""

import argparse
import json
import os
import signal

import jax
import numpy as np
import pytest

from fengshen_tpu.resilience import (FaultPlan, InjectedLoaderFault,
                                     ResilientLoader,
                                     truncate_checkpoint_step)


# -- ResilientLoader unit tests (no jit, no model) -----------------------

class _FlakyLoader:
    """Yields `data`, raising `fail_at[pos] -> times` before yielding
    that position; advance-before-yield like a storage-backed loader
    whose read fails AFTER the cursor moved when `advance_first`."""

    def __init__(self, data, fail_at, advance_first=False):
        self.data = list(data)
        self.fail_at = dict(fail_at)
        self.advance_first = advance_first
        self.pos = 0

    def skip_next(self):
        if self.pos < len(self.data):
            self.pos += 1

    def __iter__(self):
        while self.pos < len(self.data):
            i = self.pos
            if self.advance_first:
                self.pos += 1
            if self.fail_at.get(i, 0) > 0:
                self.fail_at[i] -= 1
                raise IOError(f"flaky read at {i}")
            if not self.advance_first:
                self.pos += 1
            yield self.data[i]


def test_resilient_loader_retries_with_backoff():
    sleeps = []
    inner = _FlakyLoader(range(5), {2: 3})
    loader = ResilientLoader(inner, max_retries=3, backoff_base=0.1,
                             sleep=sleeps.append, resumable=True)
    assert list(loader) == [0, 1, 2, 3, 4]  # nothing lost
    assert loader.retries_total == 3
    assert loader.skipped_total == 0
    assert len(sleeps) == 3
    # exponential backoff with bounded jitter: base*2^(n-1) .. 1.25x
    for n, s in enumerate(sleeps, start=1):
        assert 0.1 * 2 ** (n - 1) <= s <= 0.1 * 2 ** (n - 1) * 1.25


def test_resilient_loader_exhausts_then_raises():
    inner = _FlakyLoader(range(3), {1: 99})
    loader = ResilientLoader(inner, max_retries=2, backoff_base=0,
                             sleep=lambda s: None, resumable=True)
    with pytest.raises(IOError):
        list(loader)
    assert loader.retries_total == 3  # 1 initial + 2 retries counted


def test_resilient_loader_skip_budget():
    # a batch failing deterministically at the SAME position exhausts
    # its retries, then the skip budget kicks in via the cooperative
    # skip_next() protocol: the poison batch is dropped, the epoch
    # completes
    events = []
    inner = _FlakyLoader(range(4), {1: 99})
    loader = ResilientLoader(inner, max_retries=1, backoff_base=0,
                             skip_batch_budget=1, sleep=lambda s: None,
                             log=events.append, resumable=True)
    assert list(loader) == [0, 2, 3]
    assert loader.skipped_total == 1
    kinds = [e["event"] for e in events]
    assert "loader_retry" in kinds and "loader_skip_batch" in kinds


class _RestartingLoader:
    """Restarts from batch 0 on every iter() — like a val loader over
    `_SimpleBatchSampler`; deterministic, not mid-epoch resumable."""

    def __init__(self, data, fail_at):
        self.data = list(data)
        self.fail_at = dict(fail_at)

    def __iter__(self):
        for i, x in enumerate(self.data):
            if self.fail_at.get(i, 0) > 0:
                self.fail_at[i] -= 1
                raise IOError(f"flaky read at {i}")
            yield x


def test_resilient_loader_fast_forwards_non_resumable():
    """A non-resumable (restart-on-iter) loader must not re-deliver
    already-yielded batches after a retry — the val path would
    double-count losses otherwise."""
    inner = _RestartingLoader(range(4), {2: 1})
    loader = ResilientLoader(inner, max_retries=2, backoff_base=0,
                             sleep=lambda s: None)
    assert not loader.resumable  # auto-detected: no stateful sampler
    assert list(loader) == [0, 1, 2, 3]  # no [0, 1, 0, 1, ...] replay
    assert loader.retries_total == 1


def test_resilient_loader_retries_same_batch_on_real_dataloader():
    """The production path: DataLoader + stateful PretrainingRandomSampler
    with a dataset whose fetch fails transiently. The sampler advances
    only AFTER a batch is fully delivered, so the retry re-fetches the
    SAME indices — no data is silently dropped."""
    from fengshen_tpu.data import (DataLoader, PretrainingRandomSampler)

    fail = {"remaining": 2, "at_call": 5}
    calls = {"n": 0}

    class FlakyDS:
        def __len__(self):
            return 16

        def __getitem__(self, i):
            calls["n"] += 1
            if calls["n"] == fail["at_call"] and fail["remaining"] > 0:
                fail["remaining"] -= 1
                fail["at_call"] = calls["n"] + 1  # fail the retry once too
                raise IOError("flaky storage read")
            return {"input_ids": [i] * 4}

    sampler = PretrainingRandomSampler(16, 0, 4, 0, 1, epoch_seed=3)
    loader = ResilientLoader(DataLoader(FlakyDS(), sampler,
                                        global_batch_size=4),
                             max_retries=3, backoff_base=0,
                             sleep=lambda s: None)
    assert loader.resumable  # auto-detected from the stateful sampler
    got = [b["input_ids"][:, 0].tolist() for b in loader]

    # clean reference epoch: identical batches, nothing dropped
    ref_sampler = PretrainingRandomSampler(16, 0, 4, 0, 1, epoch_seed=3)
    ref = [sorted(idx) for idx in ref_sampler]
    assert [sorted(b) for b in got] == ref
    assert loader.retries_total == 2


def test_resilient_loader_skip_budget_on_real_dataloader():
    """A deterministically-poisoned sample on the production DataLoader:
    retries exhaust (unconsume keeps retrying the SAME batch), then the
    skip budget drops exactly that batch via DataLoader.skip_next and
    the epoch completes."""
    from fengshen_tpu.data import DataLoader, PretrainingRandomSampler

    POISON = 11

    class PoisonDS:
        def __len__(self):
            return 16

        def __getitem__(self, i):
            if i == POISON:
                raise IOError("permanently corrupt row")
            return {"input_ids": [i] * 4}

    sampler = PretrainingRandomSampler(16, 0, 4, 0, 1, epoch_seed=3)
    loader = ResilientLoader(DataLoader(PoisonDS(), sampler,
                                        global_batch_size=4),
                             max_retries=2, backoff_base=0,
                             skip_batch_budget=1, sleep=lambda s: None)
    got = [i for b in loader for i in b["input_ids"][:, 0].tolist()]
    assert loader.skipped_total == 1
    assert POISON not in got
    # the 3 clean batches (12 rows) all arrived, nothing else dropped
    assert len(got) == 12 and len(set(got)) == 12
    # the skip advanced the sampler cursor past the poison batch too
    assert sampler.consumed_samples == 16


def test_resilient_loader_no_fake_skips_on_non_resumable():
    """A restart-on-iter loader re-produces a poison batch on every
    re-entry, so no wrapper can skip it: the budget must NOT be burned
    on skips that never happen — the error surfaces instead."""
    inner = _RestartingLoader(range(4), {2: 99})
    loader = ResilientLoader(inner, max_retries=1, backoff_base=0,
                             skip_batch_budget=3, sleep=lambda s: None)
    with pytest.raises(IOError):
        list(loader)
    assert loader.skipped_total == 0  # no phantom skips logged


def test_resilient_loader_proxies_loader_surface():
    class L:
        num_samples = 12
        global_batch_size = 4

        def __init__(self):
            self.epoch = None

        def __len__(self):
            return 3

        def set_epoch(self, e):
            self.epoch = e

        def peek(self):
            return "peeked"

        def __iter__(self):
            return iter([])

    loader = ResilientLoader(L(), max_retries=1)
    assert len(loader) == 3
    assert loader.num_samples == 12
    assert loader.global_batch_size == 4
    assert loader.peek() == "peeked"
    loader.set_epoch(7)
    assert loader.loader.epoch == 7


# -- trainer-integrated tests (tiny model, CPU mesh) ---------------------

def _parse(argv):
    from fengshen_tpu.data.universal_datamodule import UniversalDataModule
    from fengshen_tpu.models.model_utils import add_module_args
    from fengshen_tpu.trainer import add_trainer_args
    from fengshen_tpu.utils import UniversalCheckpoint
    parser = argparse.ArgumentParser()
    add_module_args(parser)
    add_trainer_args(parser)
    UniversalDataModule.add_data_specific_args(parser)
    UniversalCheckpoint.add_argparse_args(parser)
    return parser.parse_args(argv)


def _tiny_cfg():
    from fengshen_tpu.models.llama import LlamaConfig
    return LlamaConfig(vocab_size=64, hidden_size=16,
                       intermediate_size=32, num_hidden_layers=1,
                       num_attention_heads=2,
                       max_position_embeddings=32, dtype="float32")


def _dataset(n=64, seq=16, seed=0):
    rng = np.random.RandomState(seed)
    rows = [{"input_ids": rng.randint(0, 63, seq).tolist()}
            for _ in range(n)]

    class DS:
        def __len__(self):
            return len(rows)

        def __getitem__(self, i):
            return rows[i]

    return DS()


def _fit(tmp_path, argv, plan=None, with_ckpt=True, fault_datamodule=False):
    from fengshen_tpu.data import UniversalDataModule
    from fengshen_tpu.models.llama import LlamaForCausalLM
    from fengshen_tpu.trainer import Trainer
    from fengshen_tpu.trainer.modules import CausalLMModule
    from fengshen_tpu.utils import UniversalCheckpoint

    args = _parse(["--train_batchsize", "4", "--learning_rate", "1e-3",
                   "--warmup_steps", "1", "--log_every_n_steps", "1",
                   "--default_root_dir", str(tmp_path)] + argv)
    cfg = _tiny_cfg()
    module = CausalLMModule(args, LlamaForCausalLM(cfg), cfg)
    dm = UniversalDataModule(args=args, datasets={"train": _dataset()})
    trainer = Trainer(args)
    if with_ckpt:
        trainer.callbacks.append(UniversalCheckpoint(args))
    if plan is not None:
        plan.install(trainer)
        if fault_datamodule:
            plan.wrap_datamodule(dm)
    state = trainer.fit(module, dm)
    return trainer, state, module


def _events(tmp_path):
    with open(os.path.join(tmp_path, "metrics.jsonl")) as f:
        return [json.loads(line) for line in f]


def test_nan_step_guard_skips_update(tmp_path):
    """Injected NaN loss at (0-based) step 2: the update is skipped —
    final params are bit-for-bit the params checkpointed at the end of
    step 2 (global) — and bad_step_count lands in state + metrics.
    Composes with --accumulate_grad_batches."""
    ck = tmp_path / "ck"
    plan = FaultPlan(nan_loss_at_steps={2})
    trainer, state, _ = _fit(
        tmp_path,
        ["--max_steps", "3", "--accumulate_grad_batches", "2",
         "--every_n_train_steps", "2",
         "--save_ckpt_path", str(ck), "--load_ckpt_path",
         str(tmp_path / "none")],
        plan=plan)
    assert trainer.global_step == 3 and int(state.step) == 3
    assert int(state.bad_step_count) == 1

    import orbax.checkpoint as ocp
    mgr = ocp.CheckpointManager(str(ck))
    restored = mgr.restore(
        2, args=ocp.args.Composite(state=ocp.args.StandardRestore()))
    good = jax.tree_util.tree_leaves(restored["state"]["params"])
    final = jax.tree_util.tree_leaves(
        jax.tree_util.tree_map(np.asarray, state.params))
    assert len(good) == len(final)
    for a, b in zip(good, final):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    rows = [e for e in _events(tmp_path) if "bad_step_count" in e]
    assert rows and rows[-1]["bad_step_count"] == 1
    assert not np.isfinite(rows[-1]["loss"])  # the NaN was real


def test_nan_step_guard_under_steps_per_execution(tmp_path):
    """The guard lives inside the lax.scan body, so a bad substep in a
    K-step execution skips ONLY its own update and the cumulative
    bad_step_count survives the scan."""
    plan = FaultPlan(nan_loss_at_steps={2})
    trainer, state, _ = _fit(
        tmp_path, ["--max_steps", "4", "--steps_per_execution", "2"],
        plan=plan, with_ckpt=False)
    assert trainer.global_step == 4 and int(state.step) == 4
    assert int(state.bad_step_count) == 1
    leaves = jax.tree_util.tree_leaves(state.params)
    assert all(np.isfinite(np.asarray(leaf)).all() for leaf in leaves)


def test_rewind_after_consecutive_bad_steps(tmp_path):
    """K consecutive guarded-away steps trigger a logged rewind: restore
    the last checkpoint, advance consumed_samples past the offending
    window, finish the run clean."""
    ck = tmp_path / "ck"
    plan = FaultPlan(nan_loss_at_steps={1, 2})
    trainer, state, _ = _fit(
        tmp_path,
        ["--max_steps", "4", "--every_n_train_steps", "2",
         "--max_consecutive_bad_steps", "2",
         "--save_ckpt_path", str(ck), "--load_ckpt_path", str(ck)],
        plan=plan)
    assert trainer.global_step == 4 and int(state.step) == 4
    assert int(state.bad_step_count) == 2
    rewinds = [e for e in _events(tmp_path) if e.get("event") == "rewind"]
    assert len(rewinds) == 1
    assert rewinds[0]["from_step"] == 3 and rewinds[0]["to_step"] == 2
    assert ("nan_disarmed", [1, 2]) in plan.fired
    # clean run consumes 4 batches x 4 rows; the rewound run paid 1
    # extra (skipped) batch for the bad window
    assert trainer.consumed_samples == 20
    leaves = jax.tree_util.tree_leaves(state.params)
    assert all(np.isfinite(np.asarray(leaf)).all() for leaf in leaves)


def test_loader_fault_retry_completes_fit(tmp_path):
    """A train loader raising twice (transiently) completes fit under
    --loader_max_retries, batch-for-batch identical to a clean run."""
    clean_args = ["--max_steps", "3", "--loader_max_retries", "3",
                  "--loader_backoff_base", "0.01"]
    _, clean_state, _ = _fit(tmp_path / "clean", clean_args,
                             with_ckpt=False)

    plan = FaultPlan(loader_raise_at={1: 2})
    trainer, state, _ = _fit(tmp_path / "faulty", clean_args, plan=plan,
                             with_ckpt=False, fault_datamodule=True)
    assert trainer.global_step == 3 and int(state.step) == 3
    assert plan.loader_raise_at == {1: 0}  # both injections consumed
    retries = [e for e in _events(tmp_path / "faulty")
               if e.get("event") == "loader_retry"]
    assert len(retries) == 2
    assert all("InjectedLoaderFault" in e["error"] for e in retries)
    for a, b in zip(jax.tree_util.tree_leaves(clean_state.params),
                    jax.tree_util.tree_leaves(state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_skip_budget_fit_keeps_consumed_samples_aligned(tmp_path):
    """--loader_skip_batches alone (no retries) wraps the loader, drops
    the poison batch, and folds the skipped stream position into
    trainer.consumed_samples so resumes stay aligned with the sampler."""
    from fengshen_tpu.data import UniversalDataModule
    from fengshen_tpu.models.llama import LlamaForCausalLM
    from fengshen_tpu.trainer import Trainer
    from fengshen_tpu.trainer.modules import CausalLMModule

    args = _parse(["--train_batchsize", "4", "--learning_rate", "1e-3",
                   "--warmup_steps", "1", "--log_every_n_steps", "1",
                   "--default_root_dir", str(tmp_path),
                   "--max_steps", "3", "--max_epochs", "3",
                   "--loader_max_retries", "0",
                   "--loader_skip_batches", "1"])
    rng = np.random.RandomState(0)
    rows = [{"input_ids": rng.randint(0, 63, 16).tolist()}
            for _ in range(64)]
    poison = {"row": None, "remaining": 1}

    class PoisonDS:
        def __len__(self):
            return 64

        def __getitem__(self, i):
            if i == poison["row"] and poison["remaining"] > 0:
                poison["remaining"] -= 1
                raise IOError("transient poison row")
            return rows[i]

    cfg = _tiny_cfg()
    trainer = Trainer(args)
    module = CausalLMModule(args, LlamaForCausalLM(cfg), cfg)
    dm = UniversalDataModule(args=args, datasets={"train": PoisonDS()})
    dm.trainer = trainer
    # poison a row of the SECOND batch the run's own sampler will draw
    probe = dm.train_dataloader()
    batches = [b for _, b in zip(range(2), iter(probe.sampler))]
    poison["row"] = batches[1][0]
    world_batch = probe.global_batch_size

    state = trainer.fit(module, dm)
    assert int(state.step) == 3
    assert poison["remaining"] == 0  # the poison actually fired
    skips = [e for e in _events(tmp_path)
             if e.get("event") == "loader_skip_batch"]
    assert len(skips) == 1
    # 3 trained + 1 skipped global batches all count as consumed
    assert trainer.consumed_samples == 4 * world_batch


def test_loader_fault_exhausted_raises(tmp_path):
    """More failures than the retry bound (and no skip budget) must
    surface — a dead loader is an error, not a zero-step epoch."""
    plan = FaultPlan(loader_raise_at={1: 99})
    with pytest.raises(InjectedLoaderFault):
        _fit(tmp_path, ["--max_steps", "3", "--loader_max_retries", "2",
                        "--loader_backoff_base", "0"],
             plan=plan, with_ckpt=False, fault_datamodule=True)


def test_truncated_checkpoint_falls_back_to_previous(tmp_path):
    """A truncated newest checkpoint is rejected (logged) and restore
    falls back to the previous step instead of crashing."""
    from fengshen_tpu.models.llama import LlamaForCausalLM
    from fengshen_tpu.trainer import Trainer
    from fengshen_tpu.trainer.modules import CausalLMModule
    from fengshen_tpu.utils import UniversalCheckpoint

    ck = tmp_path / "ck"
    argv = ["--max_steps", "4", "--every_n_train_steps", "2",
            "--save_ckpt_path", str(ck), "--load_ckpt_path", str(ck)]
    _fit(tmp_path, argv)

    removed = truncate_checkpoint_step(str(ck), 4)
    assert removed

    args = _parse(["--train_batchsize", "4", "--default_root_dir",
                   str(tmp_path / "resume"), "--save_ckpt_path", str(ck),
                   "--load_ckpt_path", str(ck)])
    cfg = _tiny_cfg()
    trainer2 = Trainer(args)
    trainer2.callbacks.append(UniversalCheckpoint(args))
    module2 = CausalLMModule(args, LlamaForCausalLM(cfg), cfg)
    trainer2.restore_for_predict(module2)
    assert trainer2.global_step == 2  # fell back past the corrupt 4
    rejected = [e for e in _events(tmp_path / "resume")
                if e.get("event") == "checkpoint_restore_rejected"]
    assert len(rejected) == 1 and rejected[0]["ckpt_step"] == 4
    # the owned corrupt step was deleted, so a future boundary save at
    # step 4 is possible again instead of shadowed forever
    import orbax.checkpoint as ocp
    assert 4 not in ocp.CheckpointManager(str(ck)).all_steps()


def test_structural_mismatch_surfaces_immediately(tmp_path):
    """Restoring into a differently-shaped model is a config error: it
    must raise CheckpointStructureMismatch at once, not burn a full
    restore attempt per step before failing with 'corrupt'."""
    import optax

    from fengshen_tpu.trainer.train_state import TrainState
    from fengshen_tpu.utils import UniversalCheckpoint
    from fengshen_tpu.utils.universal_checkpoint import (
        CheckpointStructureMismatch)

    ck = tmp_path / "ck"
    _fit(tmp_path, ["--max_steps", "4", "--every_n_train_steps", "2",
                    "--save_ckpt_path", str(ck),
                    "--load_ckpt_path", str(ck)])

    args = _parse(["--train_batchsize", "4", "--default_root_dir",
                   str(tmp_path), "--save_ckpt_path", str(ck),
                   "--load_ckpt_path", str(ck)])
    wrong = TrainState.create(
        apply_fn=lambda: None,
        params={"w": np.zeros((2, 2), np.float32)},
        tx=optax.adamw(1e-3))

    class _T:
        global_step = 0
        consumed_samples = 0

    with pytest.raises(CheckpointStructureMismatch):
        UniversalCheckpoint(args).maybe_restore(wrong, _T())


def test_kill_and_resume_matches_uninterrupted(tmp_path):
    """Crash-at-step-k via a REAL SIGTERM + resume must finish with
    final params bit-for-bit identical to an uninterrupted run: the
    autosaved checkpoint, the resumable sampler, and the step-folded
    rng together make recovery exact."""
    prev = signal.getsignal(signal.SIGTERM)
    try:
        _, state_a, _ = _fit(tmp_path / "a", ["--max_steps", "6"],
                             with_ckpt=False)

        ck = tmp_path / "b" / "ck"
        argv = ["--max_steps", "6", "--save_ckpt_path", str(ck),
                "--load_ckpt_path", str(ck)]
        plan = FaultPlan(sigterm_at_step=3)
        trainer1, state1, _ = _fit(tmp_path / "b", argv, plan=plan)
        assert trainer1.global_step == 3 and int(state1.step) == 3
        assert plan.fired == [("sigterm", 3)]
        assert any(e.get("event") == "preempted_saved"
                   for e in _events(tmp_path / "b"))

        trainer2, state2, _ = _fit(tmp_path / "b", argv)
        assert trainer2.global_step == 6 and int(state2.step) == 6
    finally:
        signal.signal(signal.SIGTERM, prev)

    leaves_a = jax.tree_util.tree_leaves(state_a.params)
    leaves_b = jax.tree_util.tree_leaves(state2.params)
    assert len(leaves_a) == len(leaves_b)
    for a, b in zip(leaves_a, leaves_b):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_sigterm_chains_previous_handler():
    """Trainer's preemption handler must chain the handler it replaced
    (SLURM re-queue shims and pod managers keep working)."""
    from fengshen_tpu.trainer import Trainer

    calls = []
    orig = signal.getsignal(signal.SIGTERM)
    signal.signal(signal.SIGTERM, lambda s, f: calls.append(s))
    try:
        args = _parse(["--default_root_dir", "/tmp/fstpu_sigterm_test"])
        trainer = Trainer(args)
        os.kill(os.getpid(), signal.SIGTERM)
        assert trainer._preempted
        assert calls == [signal.SIGTERM]
    finally:
        signal.signal(signal.SIGTERM, orig)


def test_save_verifies_commit(tmp_path):
    """A sync save whose step never committed must raise, not let the
    manager prune good older steps around a phantom restore point."""
    from fengshen_tpu.utils import UniversalCheckpoint

    args = _parse(["--save_ckpt_path", str(tmp_path / "ck"),
                   "--default_root_dir", str(tmp_path)])
    cb = UniversalCheckpoint(args)

    class _Mgr:
        def save(self, step, args=None):
            pass  # lost write

        def wait_until_finished(self):
            pass

        def all_steps(self, read=False):
            return []

    cb._manager = _Mgr()

    class _T:
        global_step = 5
        consumed_samples = 20

    class _S:
        params = {"w": np.zeros(2)}
        opt_state = ()

    with pytest.raises(RuntimeError, match="did not commit"):
        cb.save(_S(), _T(), sync=True)
