"""Paged + int8-quantized KV cache for the serving engine (ISSUE 6).

The load-bearing contracts:

- greedy decode through the paged fp32 pool is TOKEN-IDENTICAL to
  sequential `utils.generate.generate` — staggered admission, block
  reclaim, scan_layers and GQA covered;
- ONE decode compilation per (layout, dtype) engine and one prefill
  per bucket — paging must not reintroduce per-request retraces;
- int8 KV never flips a CONFIDENT fp decision (the margin-aware bar:
  a disagreement is only legal where the fp top-2 logit gap is within
  the measured int8 rounding noise);
- admission switches from free-slot to enough-free-blocks, with
  deferral (not loss) when the pool is exhausted and block reclaim on
  completion/cancel;
- the host allocator is exact: no double-free, deterministic ids,
  null block never handed out.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from fengshen_tpu.ops.int8_matmul import dequantize_kv, quantize_kv
from fengshen_tpu.serving import (BlockAllocator, ContinuousBatchingEngine,
                                  EngineConfig, QueueFull,
                                  init_pool_cache, reset_free_slots)
from fengshen_tpu.utils.generate import generate


def _make(scan=False, kv_heads=None):
    cfg = LlamaConfig(vocab_size=97, hidden_size=32, intermediate_size=64,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=kv_heads,
                      max_position_embeddings=64, dtype="float32",
                      scan_layers=scan)
    model = LlamaForCausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 4), jnp.int32))["params"]
    return model, params


@pytest.fixture(scope="module")
def tiny():
    return _make()


def _prompts(lengths, seed=0):
    rng = np.random.RandomState(seed)
    return [rng.randint(3, 96, n).astype(np.int32) for n in lengths]


def _ref(model, params, prompt, max_new, **kw):
    out = np.asarray(generate(model, params, jnp.asarray(prompt)[None],
                              max_new_tokens=max_new, **kw))
    return out[0, len(prompt):].tolist()


PAGED = dict(kv_layout="paged", kv_block_size=16)


# ---- allocator ----------------------------------------------------------

def test_block_allocator_exact_accounting():
    a = BlockAllocator(6)            # block 0 reserved → 5 usable
    assert a.total_blocks == 5 and a.free_blocks == 5
    first = a.alloc(2)
    assert first == [1, 2]           # deterministic lowest-first
    assert 0 not in first            # the null block is never handed out
    assert a.alloc(4) is None        # 3 left — all-or-nothing
    assert a.free_blocks == 3
    a.free(first)
    assert a.free_blocks == 5 and a.used_blocks == 0
    with pytest.raises(ValueError):
        a.free([1])                  # double-free must raise
    with pytest.raises(ValueError):
        a.alloc(0)
    with pytest.raises(ValueError):
        BlockAllocator(1)            # null block + nothing allocatable


# ---- greedy parity (the tentpole contract) ------------------------------

def test_paged_greedy_parity_staggered_admission(tiny):
    """Requests admitted at different ticks, spanning both buckets,
    more requests than slots (block reclaim in the middle), decode
    token-identical to sequential generate."""
    model, params = tiny
    prompts = _prompts((5, 11, 16, 7))
    refs = [_ref(model, params, p, 10) for p in prompts]
    eng = ContinuousBatchingEngine(
        model, params, EngineConfig(num_slots=2, buckets=(8, 16),
                                    max_new_tokens=10, max_queue=16,
                                    **PAGED))
    r0 = eng.submit(prompts[0])
    r1 = eng.submit(prompts[1])
    for _ in range(3):
        eng.step()
    r2 = eng.submit(prompts[2])
    r3 = eng.submit(prompts[3])
    eng.run_until_idle()
    for req, ref in zip((r0, r1, r2, r3), refs):
        assert req.tokens == ref
        assert req.state == "finished"


def test_paged_parity_virtual_lane_shorter_than_max_len(tiny):
    """kv_max_blocks_per_slot below max_len/block_size shrinks the
    virtual lane (the gather is over fewer positions than the slot
    pool reads) — tokens must not change."""
    model, params = tiny
    prompts = _prompts((5, 9), seed=7)
    refs = [_ref(model, params, p, 8) for p in prompts]
    eng = ContinuousBatchingEngine(
        model, params, EngineConfig(num_slots=2, buckets=(16,),
                                    max_new_tokens=8, max_queue=4,
                                    kv_layout="paged", kv_block_size=8,
                                    kv_max_blocks_per_slot=3))
    assert eng.seq_capacity == 24 < eng.max_len
    assert eng.generate_all(prompts) == refs


@pytest.mark.parametrize("scan,kv_heads", [(True, 2), (False, 2),
                                           (True, None)])
def test_paged_parity_scan_and_gqa(scan, kv_heads):
    model, params = _make(scan=scan, kv_heads=kv_heads)
    prompts = _prompts((5, 11, 16), seed=1)
    refs = [_ref(model, params, p, 8) for p in prompts]
    eng = ContinuousBatchingEngine(
        model, params, EngineConfig(num_slots=2, buckets=(8, 16),
                                    max_new_tokens=8, max_queue=8,
                                    **PAGED))
    assert eng.generate_all(prompts) == refs


def test_paged_parity_with_eos_and_controls(tiny):
    """eos mid-stream and repetition penalty both ride the paged path
    unchanged (per-slot cursors into the [S, virt_len] history)."""
    model, params = tiny
    prompt = _prompts((9,), seed=3)[0]
    free_run = _ref(model, params, prompt, 12)
    eos = free_run[3]
    ref = _ref(model, params, prompt, 12, eos_token_id=eos)
    ref = ref[:ref.index(eos) + 1]
    eng = ContinuousBatchingEngine(
        model, params, EngineConfig(num_slots=2, buckets=(16,),
                                    max_new_tokens=12, max_queue=4,
                                    eos_token_id=eos, **PAGED))
    req = eng.submit(prompt)
    eng.run_until_idle()
    assert req.tokens == ref and req.finish_reason == "eos"

    pen_ref = [_ref(model, params, p, 8, repetition_penalty=1.5)
               for p in _prompts((6, 13), seed=5)]
    eng = ContinuousBatchingEngine(
        model, params, EngineConfig(num_slots=2, buckets=(8, 16),
                                    max_new_tokens=8, max_queue=4,
                                    repetition_penalty=1.5, **PAGED))
    assert eng.generate_all(_prompts((6, 13), seed=5)) == pen_ref


# ---- compile counts (no per-request retraces) ---------------------------

@pytest.mark.parametrize("kv_dtype", ["fp32", "int8"])
def test_paged_decode_compiles_once_across_reclaim(tiny, kv_dtype):
    """One decode program per (layout, dtype) engine for its whole
    lifetime — across staggered admission, block reclaim, and both
    prefill buckets (one compile each); assign compiles once."""
    model, params = tiny
    eng = ContinuousBatchingEngine(
        model, params, EngineConfig(num_slots=2, buckets=(8, 16),
                                    max_new_tokens=6, max_queue=16,
                                    kv_dtype=kv_dtype, **PAGED))
    if not hasattr(eng._decode_jit, "_cache_size"):
        pytest.skip("jit cache introspection unavailable")
    eng.warmup()
    prompts = _prompts((5, 11, 16, 7, 3, 9))
    reqs = [eng.submit(p) for p in prompts[:3]]
    for _ in range(4):
        eng.step()
    reqs += [eng.submit(p) for p in prompts[3:]]
    eng.run_until_idle()
    assert all(r.state == "finished" for r in reqs)
    assert eng._decode_jit._cache_size() == 1
    assert eng._prefill_jit._cache_size() == 2
    assert eng._assign_jit._cache_size() == 1


# ---- int8 KV: the margin-aware agreement bar ----------------------------

def _kv_roundtrip_noise(model, params, seq):
    """Direct measurement of the int8-KV logit perturbation: prime a
    fp cache on `seq[:-1]`, round-trip its K/V through the pool's
    per-(token, head) quantization, decode one step both ways, and
    return the max |logit| difference. This is the noise floor any
    margin must beat before a flipped argmax counts as a bug."""
    from fengshen_tpu.utils.generate import _prefill_cache

    ids = jnp.asarray(seq[:-1], jnp.int32)[None]
    mask = jnp.ones_like(ids)
    pos = jnp.arange(ids.shape[1])[None]
    _, cache = _prefill_cache(model, params, ids, mask, pos)

    def roundtrip(path, leaf):
        name = getattr(path[-1], "key", "")
        if name in ("cached_key", "cached_value"):
            return dequantize_kv(*quantize_kv(leaf), leaf.dtype)
        return leaf
    cache_q = jax.tree_util.tree_map_with_path(roundtrip, cache)

    def step(cache):
        logits, _ = model.apply(
            {"params": params, "cache": cache},
            jnp.asarray(seq[-1:], jnp.int32)[None],
            attention_mask=mask,
            position_ids=jnp.asarray([[len(seq) - 1]]),
            init_cache=True, mutable=["cache"])
        return logits[0, -1]
    return float(jnp.max(jnp.abs(step(cache) - step(cache_q))))


def assert_margin_aware_agreement(model, params, prompt, ref_tokens,
                                  test_tokens, noise_scale=4.0):
    """int8 noise must never flip a CONFIDENT decision: walk both
    streams; positions after the first divergence are autoregressive
    drift and not comparable, so only the first disagreement is
    judged — the fp top-2 logit margin there (teacher-forced on the
    shared prefix) must sit within `noise_scale` x the measured
    round-trip noise."""
    assert len(ref_tokens) == len(test_tokens)
    for t, (a, b) in enumerate(zip(ref_tokens, test_tokens)):
        if a == b:
            continue
        seq = np.concatenate([prompt, ref_tokens[:t + 1]])
        logits = np.asarray(model.apply(
            {"params": params}, jnp.asarray(seq, jnp.int32)[None]))[0]
        step = logits[len(prompt) + t - 1]
        top2 = np.sort(step)[-2:]
        margin = float(top2[1] - top2[0])
        noise = _kv_roundtrip_noise(model, params, seq[:len(prompt) + t])
        assert margin <= noise_scale * noise, (
            f"int8 KV flipped a confident position {t}: fp margin "
            f"{margin:.4f} vs noise floor {noise:.4f}")
        return
    # full agreement: the bar is trivially met


@pytest.mark.parametrize("layout_kw", [PAGED, {}],
                         ids=["paged", "slot"])
def test_int8_kv_margin_aware_agreement(tiny, layout_kw):
    model, params = tiny
    prompts = _prompts((5, 11, 16, 7), seed=11)
    refs = [_ref(model, params, p, 10) for p in prompts]
    eng = ContinuousBatchingEngine(
        model, params, EngineConfig(num_slots=2, buckets=(8, 16),
                                    max_new_tokens=10, max_queue=16,
                                    kv_dtype="int8", **layout_kw))
    outs = eng.generate_all(prompts)
    for prompt, ref, out in zip(prompts, refs, outs):
        assert_margin_aware_agreement(model, params, prompt, ref, out)


# ---- scheduler: blocks as the admission currency ------------------------

def test_block_exhaustion_defers_then_serves(tiny):
    """4 slots but only 2 requests' worth of blocks: admission is
    bounded by the pool, deferred requests are NOT lost, and reclaim
    drains the queue with token-identical results."""
    model, params = tiny
    prompts = _prompts((6, 6, 6, 6), seed=2)
    refs = [_ref(model, params, p, 8) for p in prompts]
    eng = ContinuousBatchingEngine(
        model, params, EngineConfig(num_slots=4, buckets=(8,),
                                    max_new_tokens=8, max_queue=16,
                                    kv_layout="paged", kv_block_size=16,
                                    kv_num_blocks=3))
    reqs = [eng.submit(p) for p in prompts]
    eng.step()
    st = eng.stats()
    assert st["slots_active"] == 2          # pool-bounded, not slots
    assert st["kv_blocks_used"] == 2
    assert st["deferred_admissions"] == 1
    eng.step()
    # the same waiting head is ONE deferral event, not one per tick
    assert eng.stats()["deferred_admissions"] == 1
    eng.run_until_idle()
    assert [r.tokens for r in reqs] == refs
    st = eng.stats()
    assert st["kv_blocks_used"] == 0        # everything reclaimed
    assert st["slots_active_peak"] == 2
    # r2 and r3 both fit after the first reclaim: one deferral total
    assert st["deferred_admissions"] == 1


def test_block_exhaustion_backpressures_submit_as_queue_full(tiny):
    """OOM-of-blocks maps onto the existing QueueFull path: with no
    engine thread draining, a full pool leaves requests queued and the
    bounded queue 429s the next submit."""
    model, params = tiny
    eng = ContinuousBatchingEngine(
        model, params, EngineConfig(num_slots=4, buckets=(8,),
                                    max_new_tokens=8, max_queue=2,
                                    kv_layout="paged", kv_block_size=16,
                                    kv_num_blocks=2))
    p = _prompts((6,))[0]
    eng.submit(p)
    eng.step()                   # head admitted, pool now exhausted
    eng.submit(p)
    eng.submit(p)                # queue at max_queue=2
    with pytest.raises(QueueFull):
        eng.submit(p)
    assert eng.stats()["rejected_queue_full"] == 1


def test_unsatisfiable_footprint_rejected_not_livelocked(tiny):
    """A request needing more blocks than the POOL has can never be
    admitted by any amount of reclaim — submit must 413 it instead of
    parking it at the queue head forever (which would also starve
    every request behind it)."""
    from fengshen_tpu.serving import PromptTooLong
    model, params = tiny
    eng = ContinuousBatchingEngine(
        model, params, EngineConfig(num_slots=2, buckets=(8, 32),
                                    max_new_tokens=32, max_queue=8,
                                    kv_layout="paged", kv_block_size=16,
                                    kv_num_blocks=4))
    # bucket 32 + 32 new = 64 tokens = 4 blocks > 3 allocatable
    with pytest.raises(PromptTooLong, match="KV blocks"):
        eng.submit(_prompts((20,))[0])
    assert eng.stats()["rejected_prompt_too_long"] == 1
    # a satisfiable request still sails through
    req = eng.submit(_prompts((6,))[0], max_new_tokens=4)
    eng.run_until_idle()
    assert req.state == "finished"


def test_cancel_running_paged_request_frees_blocks(tiny):
    model, params = tiny
    prompts = _prompts((5, 6), seed=4)
    ref1 = _ref(model, params, prompts[1], 4)
    eng = ContinuousBatchingEngine(
        model, params, EngineConfig(num_slots=1, buckets=(8,),
                                    max_new_tokens=50, max_queue=4,
                                    kv_layout="paged", kv_block_size=16,
                                    kv_num_blocks=5))
    r0 = eng.submit(prompts[0], max_new_tokens=50)
    r1 = eng.submit(prompts[1], max_new_tokens=4)
    eng.step()
    assert r0.state == "running"
    assert eng.stats()["kv_blocks_used"] == 4   # ceil((8+48... capped
    eng.cancel(r0.request_id)
    eng.run_until_idle()
    assert r0.state == "cancelled"
    assert r1.tokens == ref1     # reclaimed blocks decode untainted
    assert eng.stats()["kv_blocks_used"] == 0


# ---- AOT integration ----------------------------------------------------

def test_paged_engine_through_aot_cache(tiny, tmp_path):
    """The KV knobs flow into the AOT path (docs/aot_cache.md): a
    paged engine warms through the persistent executable cache, a
    SECOND paged engine in the same dir replays/deserializes it with
    token parity, and a different carving coexists as distinct
    executables (different avals → different keys — no collision,
    no wrong-executable reuse)."""
    from fengshen_tpu.aot import AotConfig, AotSetup

    model, params = tiny
    prompts = _prompts((5, 11), seed=6)
    refs = [_ref(model, params, p, 6) for p in prompts]
    cfg = EngineConfig(num_slots=2, buckets=(8, 16), max_new_tokens=6,
                       max_queue=8, **PAGED)

    def build(config):
        aot = AotSetup(AotConfig(cache_dir=str(tmp_path)))
        eng = ContinuousBatchingEngine(model, params, config, aot=aot)
        eng.warmup()
        return eng
    assert build(cfg).generate_all(prompts) == refs
    assert build(cfg).generate_all(prompts) == refs     # warm replay
    # a different carving must be a different executable, not a hit
    # on the first one's blob
    recarved = EngineConfig(num_slots=2, buckets=(8, 16),
                            max_new_tokens=6, max_queue=8,
                            kv_layout="paged", kv_block_size=8)
    assert build(recarved).generate_all(prompts) == refs


# ---- pool state & config surface ----------------------------------------

def test_kv_stats_shape_on_stats(tiny):
    """The /stats KV-utilization keys (satellite: blocks, bytes,
    fragmentation, dtype) for both layouts."""
    model, params = tiny
    slot = ContinuousBatchingEngine(
        model, params, EngineConfig(num_slots=2, buckets=(8,),
                                    max_new_tokens=4, max_queue=4))
    st = slot.stats()
    assert st["kv_layout"] == "slot" and st["kv_dtype"] == "fp32"
    assert st["kv_blocks_total"] == 2 and st["kv_block_tokens"] == 64
    # [2 slots, 64 max_len, 4 kv heads, 8 head_dim] x K+V x 2 layers
    assert st["kv_cache_bytes"] == 2 * 64 * 4 * 8 * 4 * 2 * 2

    paged = ContinuousBatchingEngine(
        model, params, EngineConfig(num_slots=2, buckets=(8,),
                                    max_new_tokens=4, max_queue=4,
                                    kv_dtype="int8", **PAGED))
    st = paged.stats()
    assert st["kv_layout"] == "paged" and st["kv_dtype"] == "int8"
    assert st["kv_blocks_total"] == paged.num_blocks - 1
    assert st["kv_block_tokens"] == 16
    # int8 pool + fp32 per-(token, head) scales
    tokens = paged.num_blocks * 16
    assert st["kv_cache_bytes"] == \
        tokens * 4 * 8 * 1 * 2 * 2 + tokens * 4 * 4 * 2 * 2
    req = paged.submit(_prompts((6,))[0])
    paged.step()
    st = paged.stats()
    assert st["kv_blocks_used"] == 1          # ceil((8 + 4) / 16)
    assert st["kv_blocks_free"] == st["kv_blocks_total"] - \
        st["kv_blocks_used"]
    assert 0.0 <= st["kv_fragmentation"] < 1.0
    paged.cancel(req.request_id)
    paged.run_until_idle()


def test_engine_config_validates_kv_knobs(tiny):
    model, params = tiny
    with pytest.raises(ValueError, match="kv_layout"):
        EngineConfig(kv_layout="pagedd")
    with pytest.raises(ValueError, match="kv_dtype"):
        EngineConfig(kv_dtype="int4")
    with pytest.raises(ValueError, match="kv_block_size"):
        EngineConfig(kv_layout="paged", kv_block_size=0)
    with pytest.raises(ValueError, match="kv_max_blocks_per_slot"):
        ContinuousBatchingEngine(
            model, params,
            EngineConfig(buckets=(8,), kv_layout="paged",
                         kv_block_size=16, kv_max_blocks_per_slot=100))
    with pytest.raises(ValueError, match="kv_block_size"):
        ContinuousBatchingEngine(
            model, params, EngineConfig(buckets=(8,), kv_layout="paged",
                                        kv_block_size=128))


def test_reset_free_slots_parks_block_tables(tiny):
    """The paged analog of the free-lane clamp: inactive lanes' table
    rows are parked on the null block so their stray writes cannot
    land in reallocated blocks."""
    model, _ = tiny
    cache = init_pool_cache(model, 3, layout="paged", kv_dtype="fp32",
                            num_blocks=9, block_size=8,
                            max_blocks_per_slot=4)

    def fill(path, leaf):
        name = getattr(path[-1], "key", "")
        if name in ("block_table", "cache_index"):
            return leaf + 5
        return leaf
    cache = jax.tree_util.tree_map_with_path(fill, cache)
    out = reset_free_slots(cache, jnp.asarray([True, False, True]))

    def check(path, leaf):
        name = getattr(path[-1], "key", "")
        if name == "block_table":
            np.testing.assert_array_equal(np.asarray(leaf)[1], 0)
            np.testing.assert_array_equal(np.asarray(leaf)[0], 5)
        elif name == "cache_index":
            np.testing.assert_array_equal(np.asarray(leaf), [5, 0, 5])
        return leaf
    jax.tree_util.tree_map_with_path(check, out)
