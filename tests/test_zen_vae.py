"""ZEN n-gram model + text-VAE tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # full-fit/e2e lane: run with -m slow or no -m filter





def test_ngram_dict_matching():
    from fengshen_tpu.models.zen import ZenNgramDict
    d = ZenNgramDict(ngrams=["机器", "学习", "机器学习"],
                     max_ngram_in_seq=8)
    chars = list("机器学习好")
    ids, pos = d.match(chars)
    assert (ids > 0).sum() == 3
    # "机器学习" covers chars 0-3
    covered = pos.sum(axis=1)
    assert covered[0] >= 2 and covered[4] == 0
    assert pos.shape == (5, 8)


def test_zen_forward_with_and_without_ngrams():
    from fengshen_tpu.models.zen import ZenConfig, ZenModel, ZenNgramDict
    cfg = ZenConfig.small_test_config(dtype="float32")
    model = ZenModel(cfg)
    ids = jnp.asarray(np.random.RandomState(0).randint(5, 120, (2, 10)),
                      jnp.int32)
    d = ZenNgramDict(ngrams=["ab"], max_ngram_in_seq=4)
    ngram_ids = jnp.asarray(np.random.RandomState(1).randint(
        0, 63, (2, 4)), jnp.int32)
    ngram_pos = jnp.asarray(np.random.RandomState(2).randint(
        0, 2, (2, 10, 4)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids, ngram_ids, ngram_pos)[
        "params"]
    h1, p1 = model.apply({"params": params}, ids, ngram_ids, ngram_pos)
    assert h1.shape == (2, 10, 32)
    # without ngram inputs the side encoder is skipped
    h0, _ = model.apply({"params": params}, ids)
    assert h0.shape == (2, 10, 32)
    assert float(jnp.abs(h1 - h0).max()) > 1e-6  # ngrams changed the output


def test_text_vae_loss_decreases_kl_structure():
    from fengshen_tpu.models.vae import (TextVAEConfig, TextVAEModel,
                                         vae_loss)
    cfg = TextVAEConfig.small_test_config()
    model = TextVAEModel(cfg)
    ids = jnp.asarray(np.random.RandomState(0).randint(1, 120, (2, 12)),
                      jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids,
                        rng=jax.random.PRNGKey(1))["params"]
    logits, mean, logvar = model.apply({"params": params}, ids,
                                       rng=jax.random.PRNGKey(2))
    assert logits.shape == (2, 12, cfg.decoder.vocab_size)
    loss, parts = vae_loss(logits, ids, mean, logvar, beta=0.5)
    assert np.isfinite(float(loss))
    assert float(parts["kl"]) >= 0
    # zero-mean unit... kl of (0,0) is 0
    z = jnp.zeros_like(mean)
    _, parts0 = vae_loss(logits, ids, z, z, beta=0.5)
    np.testing.assert_allclose(float(parts0["kl"]), 0.0, atol=1e-6)
