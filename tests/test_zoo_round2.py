"""Round-2 zoo completions: ZEN2 (relative attention + n-gram stack),
transfo_xl paraphrase/reasoning generation surfaces, CBART text-infill
(VERDICT r1 missing #5, #8)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # full-fit/e2e lane: run with -m slow or no -m filter





# -- zen2 -------------------------------------------------------------------

def test_zen2_forward_with_ngrams():
    from fengshen_tpu.models.zen2 import Zen2Config, Zen2Model
    cfg = Zen2Config.small_test_config(dtype="float32")
    model = Zen2Model(cfg)
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(3, 100, (2, 10)), jnp.int32)
    ngram_ids = jnp.asarray(rng.randint(0, 60, (2, 4)), jnp.int32)
    ngram_pos = jnp.asarray(rng.randint(0, 2, (2, 10, 4)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids, ngram_ids,
                        ngram_pos)["params"]
    hidden, pooled = model.apply({"params": params}, ids, ngram_ids,
                                 ngram_pos)
    assert hidden.shape == (2, 10, cfg.hidden_size)
    assert pooled.shape == (2, cfg.hidden_size)
    # no absolute position embedding table (relative attention instead)
    assert "position_embeddings" not in params
    assert "r_w_bias" in params["layer_0"]["attention"]


def test_zen2_relative_attention_shift_invariance():
    """The defining ZEN2-vs-ZEN1 property: with attention masked to the
    same token pattern, outputs at the pattern positions are IDENTICAL
    whether the pattern sits at the start or the end of the sequence —
    only relative offsets matter (no absolute position embeddings)."""
    from fengshen_tpu.models.zen2 import Zen2Config, Zen2Model
    cfg = Zen2Config.small_test_config(dtype="float32",
                                       hidden_dropout_prob=0.0,
                                       attention_probs_dropout_prob=0.0)
    model = Zen2Model(cfg, add_pooling_layer=False)
    pattern = [7, 8, 9, 10]
    pad = [1] * 4
    left = jnp.asarray([pattern + pad], jnp.int32)   # pattern at offset 0
    right = jnp.asarray([pad + pattern], jnp.int32)  # pattern at offset 4
    mask_l = jnp.asarray([[1] * 4 + [0] * 4], jnp.int32)
    mask_r = jnp.asarray([[0] * 4 + [1] * 4], jnp.int32)
    params = model.init(jax.random.PRNGKey(0), left)["params"]
    h_l, _ = model.apply({"params": params}, left, attention_mask=mask_l)
    h_r, _ = model.apply({"params": params}, right, attention_mask=mask_r)
    np.testing.assert_allclose(np.asarray(h_l)[0, :4],
                               np.asarray(h_r)[0, 4:], atol=1e-5)


def test_zen2_mlm_and_heads():
    from fengshen_tpu.models.zen2 import (Zen2Config, Zen2ForMaskedLM,
                                          Zen2ForTokenClassification)
    cfg = Zen2Config.small_test_config(dtype="float32")
    ids = jnp.asarray(np.random.RandomState(1).randint(3, 100, (2, 8)),
                      jnp.int32)
    mlm = Zen2ForMaskedLM(cfg)
    params = mlm.init(jax.random.PRNGKey(0), ids)["params"]
    logits = mlm.apply({"params": params}, ids)
    assert logits.shape == (2, 8, cfg.vocab_size)

    tok = Zen2ForTokenClassification(cfg, num_labels=4)
    params = tok.init(jax.random.PRNGKey(0), ids)["params"]
    assert tok.apply({"params": params}, ids).shape == (2, 8, 4)


def test_zen2_relative_embedding_values():
    """t2t layout (reference: zen2/modeling.py:367-384): [2n, dim] with
    [sin | cos] concatenated halves, offset 0 at row n."""
    from fengshen_tpu.models.zen2 import relative_sinusoidal_embedding
    emb = relative_sinusoidal_embedding(4, 8)
    assert emb.shape == (8, 8)
    # offset 0 row: sin half = 0, cos half = 1
    np.testing.assert_allclose(emb[4, :4], 0.0, atol=1e-6)
    np.testing.assert_allclose(emb[4, 4:], 1.0, atol=1e-6)
    # reference frequency: freq_i = 10000^(-i/(half-1))
    np.testing.assert_allclose(emb[5, 3], np.sin(1e-4 ** 1.0), atol=1e-6)


# -- transfo_xl variants ----------------------------------------------------

class _FakeTok:
    pad_token_id = 0
    eos_token_id = 2

    def encode(self, text):
        return [min(3 + (ord(c) % 90), 95) for c in text] + [2]

    def decode(self, ids):
        return " ".join(str(i) for i in ids if i not in (0, 2))


@pytest.fixture(scope="module")
def txl():
    from fengshen_tpu.models.transfo_xl_paraphrase import (
        TransfoXLParaphraseConfig, TransfoXLParaphraseModel)
    cfg = TransfoXLParaphraseConfig.small_test_config()
    model = TransfoXLParaphraseModel(cfg)
    ids = jnp.zeros((1, 8), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids)["params"]
    return model, params


def test_paraphrase_generate(txl):
    from fengshen_tpu.models.transfo_xl_paraphrase import (
        paraphrase_generate)
    model, params = txl
    out = paraphrase_generate(model, params, _FakeTok(),
                              ["今天天气很好", "我们去公园"],
                              max_out_seq=20)
    assert len(out) == 2
    assert all(isinstance(s, str) for s in out)


def test_reasoning_generate(txl):
    from fengshen_tpu.models.transfo_xl_reasoning import (
        abduction_generate, deduction_generate, en_to_zh)
    model, params = txl
    assert en_to_zh("a,b.") == "a，b。"
    ded = deduction_generate(model, params, _FakeTok(), "天下雨",
                             max_out_seq=20)
    abd = abduction_generate(model, params, _FakeTok(), ["地面湿了"],
                             max_out_seq=20)
    assert len(ded) == 1 and len(abd) == 1


# -- CBART text infill ------------------------------------------------------

def test_bart_text_infill_forward_and_loss():
    from fengshen_tpu.models.bart import (BartConfig, BartForTextInfill,
                                          text_infill_loss)
    cfg = BartConfig.small_test_config(dtype="float32")
    model = BartForTextInfill(cfg, num_labels=3)
    rng = np.random.RandomState(0)
    enc_ids = jnp.asarray(rng.randint(3, 100, (2, 8)), jnp.int32)
    dec_ids = jnp.asarray(rng.randint(3, 100, (2, 10)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), enc_ids, dec_ids)["params"]
    lm_logits, enc_logits = model.apply({"params": params}, enc_ids,
                                        dec_ids)
    assert lm_logits.shape == (2, 10, cfg.vocab_size)
    assert enc_logits.shape == (2, 8, 3)

    labels = jnp.where(jnp.arange(10)[None] < 9, dec_ids, -100)
    enc_labels = jnp.asarray(rng.randint(0, 3, (2, 8)), jnp.int32)
    loss, metrics = text_infill_loss(lm_logits, labels, enc_logits,
                                     enc_labels, loss_weight=0.5,
                                     label_weights=[1.0, 2.0, 2.0])
    assert np.isfinite(float(loss))
    assert metrics["encoder_loss"] > 0

    # regression variant (encoder_loss_type=1 predicts insert counts)
    model_r = BartForTextInfill(cfg, encoder_loss_type=1)
    params_r = model_r.init(jax.random.PRNGKey(0), enc_ids,
                            dec_ids)["params"]
    _, enc_reg = model_r.apply({"params": params_r}, enc_ids, dec_ids)
    assert enc_reg.shape == (2, 8, 1)
    loss_r, _ = text_infill_loss(
        lm_logits, labels, enc_reg,
        jnp.asarray(rng.randint(0, 3, (2, 8)), jnp.int32),
        encoder_loss_type=1)
    assert np.isfinite(float(loss_r))


def test_bart_text_infill_grads_reach_both_heads():
    from fengshen_tpu.models.bart import (BartConfig, BartForTextInfill,
                                          text_infill_loss)
    cfg = BartConfig.small_test_config(dtype="float32")
    model = BartForTextInfill(cfg)
    rng = np.random.RandomState(0)
    enc_ids = jnp.asarray(rng.randint(3, 100, (2, 6)), jnp.int32)
    dec_ids = jnp.asarray(rng.randint(3, 100, (2, 6)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), enc_ids, dec_ids)["params"]
    enc_labels = jnp.asarray(rng.randint(0, 3, (2, 6)), jnp.int32)

    def loss_fn(p):
        lm, enc = model.apply({"params": p}, enc_ids, dec_ids)
        return text_infill_loss(lm, dec_ids, enc, enc_labels)[0]

    g = jax.grad(loss_fn)(params)
    assert float(jnp.abs(g["classification_out"]["kernel"]).sum()) > 0
    assert float(jnp.abs(
        g["model"]["decoder_layer_0"]["self_attn"]["q_proj"]["kernel"]
    ).sum()) > 0
