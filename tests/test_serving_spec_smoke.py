"""`make serve-bench-spec` harness guard (ISSUE 7).

Fast lane: the acceptance MATH is deterministic — drafter proposals on
a synthetic repetitive history, `_spec_round_tokens`' greedy rule on
hand-built logits, and the committed-per-forward identity — so it is
pinned here with NO model forward; the tiny-shape end-to-end run only
guards the schema/wiring. The real >=1.8x committed-per-forward and
>=1.3x tokens/s bars need the default weight-memory-bound shape and
live in the slow lane.
"""

import io
import json
import os
from contextlib import redirect_stdout

import numpy as np
import pytest

import jax.numpy as jnp

TINY = {"SERVE_BENCH_SLOTS": "4", "SERVE_BENCH_REQUESTS": "4",
        "SERVE_BENCH_NEW_TOKENS": "8", "SERVE_BENCH_VOCAB": "128",
        "SERVE_BENCH_HIDDEN": "32", "SERVE_BENCH_INTER": "64",
        "SERVE_BENCH_LAYERS": "2", "SERVE_BENCH_HEADS": "4",
        "SERVE_BENCH_BUCKETS": "16,32", "SERVE_BENCH_MODE": "spec"}


def _run(monkeypatch, env: dict, tiny: bool = True) -> dict:
    from fengshen_tpu.serving import bench

    for key in list(os.environ):
        if key.startswith(("SERVE_BENCH_", "BENCH_DEGRADED")):
            monkeypatch.delenv(key)
    for key, val in {**(TINY if tiny else {}), **env}.items():
        monkeypatch.setenv(key, val)
    out = io.StringIO()
    with redirect_stdout(out):
        bench.main()
    lines = [l for l in out.getvalue().splitlines() if l.startswith("{")]
    assert lines, out.getvalue()
    return json.loads(lines[-1])


# ---- deterministic acceptance math (no model forward) -------------------

def test_spec_acceptance_math_deterministic():
    """The whole spec-tick accept pipeline on synthetic data: the
    drafter must propose the period's continuation from a repetitive
    history, the greedy rule must accept exactly the matching prefix,
    and committed-per-forward is the 1 + gamma*rate identity the bench
    reports."""
    from fengshen_tpu.serving.bench import committed_per_forward
    from fengshen_tpu.utils.generate import (_ngram_propose_lanes,
                                             _spec_round_tokens)

    # lane 0: period-2 history committed through t=6 → suffix [7, 9]
    # recurs at j=0 with whole-gamma continuation [7, 9, 7];
    # lane 1: no repeat → fallback (last token 5) repeated
    hist = jnp.asarray([[7, 9, 7, 9, 7, 9, 0, 0, 0, 0],
                        [1, 2, 3, 4, 5, 6, 0, 0, 0, 0]], jnp.int32)
    d = _ngram_propose_lanes(hist, jnp.asarray([6, 6]), 2, 3,
                             jnp.asarray([9, 5], jnp.int32))
    np.testing.assert_array_equal(np.asarray(d),
                                  [[7, 9, 7], [5, 5, 5]])

    # greedy verify on one-hot logits: lane 0's target continues
    # [7, 9, 8, ...] → accepts 2, correction 8; lane 1's target is
    # [5, 5, 5, 5] → full accept + bonus
    targets = np.array([[7, 9, 8, 1], [5, 5, 5, 5]])
    t_logits = jnp.asarray(np.eye(12, dtype=np.float32)[targets])
    n_r, w = _spec_round_tokens(t_logits, None, d,
                                jnp.zeros((2,), jnp.uint32),
                                do_sample=False)
    np.testing.assert_array_equal(np.asarray(n_r), [2, 3])
    np.testing.assert_array_equal(np.asarray(w), targets)

    # the identity the BENCH row reports: per-lane committed tokens
    # per verify = 1 + accepted; aggregated = 1 + gamma * rate
    rate = float(np.asarray(n_r).sum()) / (2 * 3)
    assert committed_per_forward(3, rate) == pytest.approx(
        np.asarray(n_r + 1).mean())
    assert committed_per_forward(4, 0.0) == 1.0
    assert committed_per_forward(4, 1.0) == 5.0
    with pytest.raises(ValueError):
        committed_per_forward(4, 1.5)


def test_make_target_wired():
    """`make serve-bench-spec` must keep pointing at the spec mode."""
    mk = open(os.path.join(os.path.dirname(__file__), "..",
                           "Makefile")).read()
    assert "serve-bench-spec:" in mk
    assert "SERVE_BENCH_MODE=spec" in mk


# ---- tiny end-to-end: schema + wiring -----------------------------------

def test_serve_bench_spec_emits_schema_row(monkeypatch):
    row = _run(monkeypatch, {})
    assert set(row) >= {"metric", "value", "unit", "vs_baseline",
                        "acceptance_rate", "spec_gamma", "spec_ngram",
                        "tokens_per_sec", "tokens_per_sec_off",
                        "speedup_vs_off", "token_identical"}
    assert row["metric"] == "serving_spec_committed_per_forward"
    assert row["mode"] == "spec"
    assert row["unit"] == "tokens/forward"
    # greedy spec output must equal the non-spec engine even at tiny
    # shapes — this is the cheap end-to-end parity guard
    assert row["token_identical"] is True
    assert 0.0 <= row["acceptance_rate"] <= 1.0
    from fengshen_tpu.serving.bench import committed_per_forward
    assert row["value"] == pytest.approx(
        committed_per_forward(row["spec_gamma"],
                              row["acceptance_rate"]), abs=1e-3)
    assert row["value"] == row["vs_baseline"]
    assert row["tokens_per_sec"] > 0 and row["tokens_per_sec_off"] > 0
    assert "degraded" not in row


def test_serve_bench_spec_degraded_flag(monkeypatch):
    row = _run(monkeypatch, {"BENCH_DEGRADED": "1"})
    assert row["degraded"] is True


@pytest.mark.slow
def test_serve_bench_spec_acceptance_bar(monkeypatch):
    """ISSUE 7 acceptance: on the default weight-memory-bound shape's
    repetitive workload at 8 concurrent, gamma=4 commits >=1.8 tokens
    per target forward and the spec engine clears >=1.3x the non-spec
    engine's aggregate tokens/s, token-identically. Slow lane (~2 min
    on CPU: probe + two engine warmups)."""
    row = _run(monkeypatch, {"SERVE_BENCH_MODE": "spec",
                             "SERVE_BENCH_BUCKETS": "32,64",
                             "SERVE_BENCH_NEW_TOKENS": "96"},
               tiny=False)
    assert row["spec_gamma"] == 4
    assert row["value"] >= 1.8, row
    assert row["speedup_vs_off"] >= 1.3, row
    assert row["token_identical"] is True, row
