"""Corpus pipeline + seq2seq example tests."""

import json



import numpy as np
import pytest

pytestmark = pytest.mark.slow  # full-fit/e2e lane: run with -m slow or no -m filter



def test_shard_and_preprocess(tmp_path):
    from fengshen_tpu.data.bert_dataloader import (shard_corpus,
                                                   preprocess_corpus)
    src = tmp_path / "corpus.jsonl"
    with open(src, "w") as f:
        for i in range(100):
            f.write(json.dumps({"text": "今天天气很好。我们去公园吧！"},
                               ensure_ascii=False) + "\n")
    shards = shard_corpus(str(src), str(tmp_path / "shards"), shard_mb=1)
    assert len(shards) >= 1
    n = preprocess_corpus(shards[0], str(tmp_path / "pre.jsonl"))
    assert n == 100
    row = json.loads(open(tmp_path / "pre.jsonl").readline())
    assert row["sentences"] == ["今天天气很好。", "我们去公园吧！"]


def test_seq2seq_collator_and_fit(tmp_path, mesh8):
    import argparse
    from fengshen_tpu.examples.summary.seq2seq_summary import (
        Seq2SeqCollator, Seq2SeqModule, build_model)
    from fengshen_tpu.data import UniversalDataModule
    from fengshen_tpu.trainer import Trainer, add_trainer_args
    from fengshen_tpu.models.model_utils import add_module_args

    class FakeTok:
        pad_token_id = 0
        eos_token_id = 1

        def encode(self, text, add_special_tokens=True):
            return [3 + (ord(c) % 90) for c in text]

    model, config = build_model("t5")
    coll = Seq2SeqCollator(FakeTok(), max_src_length=16, max_tgt_length=8)
    batch = coll([{"text": "今天天气很好", "summary": "好天"}])
    assert batch["input_ids"].shape == (1, 16)
    assert batch["decoder_input_ids"].shape == (1, 8)
    assert batch["labels"][0][batch["labels"][0] != -100][-1] == 1  # eos

    parser = argparse.ArgumentParser()
    add_module_args(parser)
    add_trainer_args(parser)
    UniversalDataModule.add_data_specific_args(parser)
    args = parser.parse_args([
        "--max_steps", "2", "--train_batchsize", "4",
        "--log_every_n_steps", "1", "--warmup_steps", "1",
        "--default_root_dir", str(tmp_path)])
    rows = [{"text": "今天天气很好", "summary": "好天"}] * 16

    class DS:
        def __len__(self):
            return 16

        def __getitem__(self, i):
            return rows[i]

    dm = UniversalDataModule(args=args, collate_fn=coll,
                             datasets={"train": DS()})
    module = Seq2SeqModule(args, model, config)
    trainer = Trainer(args)
    state = trainer.fit(module, dm)
    assert int(state.step) == 2


def test_wudao_cleaning_rules_hand_computed(tmp_path):
    """The five boundary rules + 512-repacking against literal expected
    outputs (reference: bert_dataloader/preprocessing.py:11-50)."""
    import json

    from fengshen_tpu.data.bert_dataloader import (cut_sent_file,
                                                   mark_sentence_boundaries,
                                                   repack_segments)

    # rule 1: terminal punctuation runs; doc-final sentence also splits
    assert mark_sentence_boundaries("天气好。明天呢？？好！") == \
        ["天气好。", "明天呢？？", "好！", ""]
    # rules 3/5: closing quote stays attached to its sentence
    assert mark_sentence_boundaries("他说：“不行！”然后走了。") == \
        ["他说：“不行！”", "然后走了。", ""]
    # rule 2: ascii ellipsis of >=3 dots
    assert mark_sentence_boundaries("省略...继续。") == \
        ["省略...", "继续。", ""]
    # unicode ellipsis
    assert mark_sentence_boundaries("等等……然后。") == \
        ["等等……", "然后。", ""]

    # repacking quirks: bound checked BEFORE append (may overflow), and
    # empty sentences flush
    assert repack_segments(iter(["abc", "de", "", "fg"]),
                           max_chars=4) == ["abcde", "fg"]
    assert repack_segments(iter(["123456", "78"]),
                           max_chars=4) == ["123456", "78"]

    # file level: one doc → cleaned ~8-char segments
    src = tmp_path / "docs.jsonl"
    with open(src, "w") as f:
        f.write(json.dumps({"text": "一二三。四五六！七八九？十。"},
                           ensure_ascii=False) + "\n")
    out = tmp_path / "clean.jsonl"
    n = cut_sent_file(str(src), str(out), max_chars=8)
    rows = [json.loads(x)["text"] for x in open(out, encoding="utf-8")]
    # sentences: 一二三。|四五六！|七八九？|十。|'' → pack at 8 chars:
    # "一二三。四五六！" (8, stop) → "七八九？十。" flushed by the empty
    # sentence; the final empty accumulator is emitted too (the
    # reference's unconditional last write, preprocessing.py:49-50)
    assert rows == ["一二三。四五六！", "七八九？十。", ""]
    assert n == 3


def test_auto_split_line_safe(tmp_path):
    """auto_split.sh semantics: oversized files split into -aa/-ab
    chunks on line boundaries, original removed."""
    import json
    import os

    from fengshen_tpu.data.bert_dataloader import auto_split

    big = tmp_path / "corpus.json"
    line = json.dumps({"text": "x" * 100}) + "\n"
    with open(big, "w") as f:
        for _ in range(100):
            f.write(line)
    # threshold 0MB (everything splits), chunks of ~1/3 the data
    chunks = auto_split(str(tmp_path), threshold_mb=0,
                        chunk_mb=4 * len(line) // (1024 * 1024) or 0.004)
    assert not big.exists()
    names = sorted(os.path.basename(c) for c in chunks)
    assert names[0] == "corpus-aa.json"
    # every chunk holds whole lines and the union is the original
    total = 0
    for c in chunks:
        content = open(c).read()
        assert content.endswith("\n")
        assert all(x == line.strip() for x in
                   content.strip().split("\n") if x)
        total += content.count("\n")
    assert total == 100


def test_generate_cache_arrow_split(tmp_path):
    """Per-shard 950/49/1-style split into an arrow cache
    (reference: load.py:27-103 BertDataGenerate)."""
    import json

    import datasets as hf_datasets

    from fengshen_tpu.data.bert_dataloader import (
        generate_cache_arrow, split_train_test_validation_index)

    idx = split_train_test_validation_index("950,49,1")
    assert abs(idx["train_rate"] - 0.95) < 1e-9
    assert abs(idx["test_rate"] - 0.98) < 1e-9

    shards = tmp_path / "shards"
    shards.mkdir()
    with open(shards / "s0.json", "w") as f:
        for i in range(100):
            f.write(json.dumps({"text": f"doc {i}"}) + "\n")
    saved = generate_cache_arrow(str(shards), str(tmp_path / "cache"),
                                 train_test_validation="80,10,10")
    assert len(saved) == 1
    dd = hf_datasets.load_from_disk(saved[0])
    assert set(dd) == {"train", "test", "validation"}
    assert len(dd["train"]) == 80
    assert len(dd["test"]) + len(dd["validation"]) == 20
