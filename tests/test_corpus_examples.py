"""Corpus pipeline + seq2seq example tests."""

import json



import numpy as np
import pytest

pytestmark = pytest.mark.slow  # full-fit/e2e lane: run with -m slow or no -m filter



def test_shard_and_preprocess(tmp_path):
    from fengshen_tpu.data.bert_dataloader import (shard_corpus,
                                                   preprocess_corpus)
    src = tmp_path / "corpus.jsonl"
    with open(src, "w") as f:
        for i in range(100):
            f.write(json.dumps({"text": "今天天气很好。我们去公园吧！"},
                               ensure_ascii=False) + "\n")
    shards = shard_corpus(str(src), str(tmp_path / "shards"), shard_mb=1)
    assert len(shards) >= 1
    n = preprocess_corpus(shards[0], str(tmp_path / "pre.jsonl"))
    assert n == 100
    row = json.loads(open(tmp_path / "pre.jsonl").readline())
    assert row["sentences"] == ["今天天气很好。", "我们去公园吧！"]


def test_seq2seq_collator_and_fit(tmp_path, mesh8):
    import argparse
    from fengshen_tpu.examples.summary.seq2seq_summary import (
        Seq2SeqCollator, Seq2SeqModule, build_model)
    from fengshen_tpu.data import UniversalDataModule
    from fengshen_tpu.trainer import Trainer, add_trainer_args
    from fengshen_tpu.models.model_utils import add_module_args

    class FakeTok:
        pad_token_id = 0
        eos_token_id = 1

        def encode(self, text, add_special_tokens=True):
            return [3 + (ord(c) % 90) for c in text]

    model, config = build_model("t5")
    coll = Seq2SeqCollator(FakeTok(), max_src_length=16, max_tgt_length=8)
    batch = coll([{"text": "今天天气很好", "summary": "好天"}])
    assert batch["input_ids"].shape == (1, 16)
    assert batch["decoder_input_ids"].shape == (1, 8)
    assert batch["labels"][0][batch["labels"][0] != -100][-1] == 1  # eos

    parser = argparse.ArgumentParser()
    add_module_args(parser)
    add_trainer_args(parser)
    UniversalDataModule.add_data_specific_args(parser)
    args = parser.parse_args([
        "--max_steps", "2", "--train_batchsize", "4",
        "--log_every_n_steps", "1", "--warmup_steps", "1",
        "--default_root_dir", str(tmp_path)])
    rows = [{"text": "今天天气很好", "summary": "好天"}] * 16

    class DS:
        def __len__(self):
            return 16

        def __getitem__(self, i):
            return rows[i]

    dm = UniversalDataModule(args=args, collate_fn=coll,
                             datasets={"train": DS()})
    module = Seq2SeqModule(args, model, config)
    trainer = Trainer(args)
    state = trainer.fit(module, dm)
    assert int(state.step) == 2
