"""DAVAE / GAVAE / PPVAE / Della tests: forward shapes, loss behavior,
latent round-trips, and the reference public surfaces (VERDICT r1
missing #4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # full-fit/e2e lane: run with -m slow or no -m filter


@pytest.fixture(scope="module")
def davae():
    from fengshen_tpu.models.davae import DAVAEConfig, DAVAEModel
    cfg = DAVAEConfig.small_test_config()
    model = DAVAEModel(cfg)
    ids = jnp.asarray(np.random.RandomState(0).randint(3, 100, (2, 12)),
                      jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids)["params"]
    return cfg, model, params, ids


def test_davae_forward_and_loss(davae):
    from fengshen_tpu.models.davae import davae_losses
    cfg, model, params, ids = davae
    logits, mean, logvar, latent = model.apply(
        {"params": params}, ids, rng=jax.random.PRNGKey(1))
    assert logits.shape == (2, 12, cfg.decoder.vocab_size)
    assert mean.shape == (2, cfg.latent_size)
    loss, _, metrics = davae_losses(logits, ids, mean, logvar)
    assert np.isfinite(float(loss)) and metrics["kl"] >= 0


def test_davae_adversarial_losses(davae):
    from fengshen_tpu.models.davae import LatentCritic, davae_losses
    cfg, model, params, ids = davae
    logits, mean, logvar, latent = model.apply(
        {"params": params}, ids, rng=jax.random.PRNGKey(1))
    critic = LatentCritic(hidden=16)
    cparams = critic.init(jax.random.PRNGKey(2), latent)["params"]
    prior = jax.random.normal(jax.random.PRNGKey(3), latent.shape)
    real = critic.apply({"params": cparams}, prior)
    fake = critic.apply({"params": cparams}, latent)
    vae_loss, critic_loss, metrics = davae_losses(
        logits, ids, mean, logvar, critic_real=real, critic_fake=fake)
    assert np.isfinite(float(vae_loss)) and np.isfinite(float(critic_loss))
    assert "adv" in metrics


def test_davae_simulate_roundtrip(davae):
    from fengshen_tpu.models.davae import (simulate_batch,
                                           latent_code_from_text_batch)
    cfg, model, params, ids = davae
    latent = latent_code_from_text_batch(model, params, ids)
    assert latent.shape == (2, cfg.latent_size)
    out = simulate_batch(model, params, ids, max_length=8, bos_id=1)
    assert out.shape == (2, 8)
    assert (np.asarray(out[:, 0]) == 1).all()
    assert (np.asarray(out) >= 0).all()


def test_davae_word_dropout():
    from fengshen_tpu.models.davae import word_dropout
    ids = jnp.asarray(np.arange(10, 110).reshape(2, 50), jnp.int32)
    out = word_dropout(ids, 0.5, unk_id=1, rng=jax.random.PRNGKey(0))
    frac = float((out == 1).mean())
    assert 0.2 < frac < 0.8
    out0 = word_dropout(ids, 0.0, unk_id=1, rng=jax.random.PRNGKey(0))
    np.testing.assert_array_equal(np.asarray(out0), np.asarray(ids))


def test_gavae_latent_gan_trains():
    from fengshen_tpu.models.gavae import GAVAEConfig, GAVAEModel
    cfg = GAVAEConfig.small_test_config()
    gavae = GAVAEModel(cfg)
    rng = np.random.RandomState(0)
    # two labelled latent clusters
    latents = jnp.asarray(np.concatenate([
        rng.randn(16, cfg.latent_size) + 2.0,
        rng.randn(16, cfg.latent_size) - 2.0]), jnp.float32)
    labels = jnp.asarray([0] * 16 + [1] * 16, jnp.int32)
    d_loss, g_loss = gavae.train_gan(latents, labels, steps=30)
    assert np.isfinite(d_loss) and np.isfinite(g_loss)
    sampled = gavae.sample_latents(4, label=0, seed=1)
    assert sampled.shape == (4, cfg.latent_size)


def test_gavae_generate_text_through_vae():
    from fengshen_tpu.models.davae import DAVAEModel
    from fengshen_tpu.models.gavae import GAVAEConfig, GAVAEModel
    cfg = GAVAEConfig.small_test_config()
    vae = DAVAEModel(cfg.vae)
    ids = jnp.zeros((1, 8), jnp.int32)
    vae_params = vae.init(jax.random.PRNGKey(0), ids)["params"]
    gavae = GAVAEModel(cfg, vae_model=vae, vae_params=vae_params)
    latents = jnp.asarray(np.random.RandomState(0).randn(
        8, cfg.latent_size), jnp.float32)
    gavae.train_gan(latents, jnp.zeros((8,), jnp.int32), steps=5)
    out = gavae.generate(3, max_length=6, bos_id=1)
    assert out.shape == (3, 6)


def test_ppvae_bottleneck_learns_cluster():
    from fengshen_tpu.models.ppvae import PPVAEConfig, PPVAEModel
    cfg = PPVAEConfig.small_test_config(kl_weight=1.0, ppvae_lr=3e-3)
    ppvae = PPVAEModel(cfg)
    rng = np.random.RandomState(0)
    pos = jnp.asarray(rng.randn(32, cfg.latent_dim) * 0.1 + 3.0,
                      jnp.float32)
    loss, metrics = ppvae.train_plugin(pos, steps=1500)
    # generated latents should land near the positive cluster (mean 3)
    gen = ppvae.gen_latent(16, seed=1)
    center_err = float(jnp.abs(gen.mean() - 3.0))
    assert center_err < 1.0, (center_err, metrics)


def test_ppvae_negative_repulsion_runs():
    from fengshen_tpu.models.ppvae import PPVAEConfig, PPVAEModel
    cfg = PPVAEConfig.small_test_config(gamma=0.1)
    ppvae = PPVAEModel(cfg)
    rng = np.random.RandomState(0)
    pos = jnp.asarray(rng.randn(16, cfg.latent_dim) + 2.0, jnp.float32)
    neg = jnp.asarray(rng.randn(16, cfg.latent_dim) - 2.0, jnp.float32)
    loss, metrics = ppvae.train_plugin(pos, neg, steps=20)
    assert np.isfinite(loss) and metrics["neg_loss"] >= 0


def test_della_forward_and_hierarchical_kl():
    from fengshen_tpu.models.deepvae import (DellaConfig, DellaModel,
                                             della_loss)
    cfg = DellaConfig.small_test_config()
    model = DellaModel(cfg)
    ids = jnp.asarray(np.random.RandomState(0).randint(3, 100, (2, 10)),
                      jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids)["params"]
    logits, posts, priors = model.apply({"params": params}, ids,
                                        rng=jax.random.PRNGKey(1))
    assert logits.shape == (2, 10, cfg.gpt2.vocab_size)
    assert len(posts) == cfg.gpt2.n_layer == len(priors)
    loss, metrics = della_loss(logits, ids, posts, priors)
    assert np.isfinite(float(loss)) and float(metrics["kl"]) >= 0

    # grads flow through every latent level
    def loss_fn(p):
        logits, posts, priors = model.apply({"params": p}, ids,
                                            rng=jax.random.PRNGKey(1))
        return della_loss(logits, ids, posts, priors)[0]
    g = jax.grad(loss_fn)(params)
    for i in range(cfg.gpt2.n_layer):
        gnorm = float(jnp.abs(g[f"posterior_{i}"]["kernel"]).sum())
        assert gnorm > 0, f"no grad into posterior_{i}"
