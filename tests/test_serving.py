"""Continuous-batching serving engine (fengshen_tpu/serving/).

The load-bearing contract: greedy decode through the slot pool is
TOKEN-IDENTICAL to sequential `utils.generate.generate`, for requests
admitted at different ticks, across slot reclaim, with ONE decode
compilation for the whole lifetime of the engine. Plus the scheduler's
fast-lane behaviors: bucket selection, queue overflow → rejection,
cancellation and deadlines freeing slots, metrics/stats.
"""

import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from fengshen_tpu.serving import (ContinuousBatchingEngine, EngineConfig,
                                  BucketLadder, PromptTooLong, QueueFull,
                                  rollback_slots)
from fengshen_tpu.utils.generate import generate


@pytest.fixture(scope="module")
def tiny():
    cfg = LlamaConfig(vocab_size=97, hidden_size=32, intermediate_size=64,
                      num_hidden_layers=2, num_attention_heads=4,
                      max_position_embeddings=64, dtype="float32")
    model = LlamaForCausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 4), jnp.int32))["params"]
    return model, params


def _prompts(lengths, seed=0):
    rng = np.random.RandomState(seed)
    return [rng.randint(3, 96, n).astype(np.int32) for n in lengths]


def _ref(model, params, prompt, max_new, **kw):
    """Sequential baseline: batch-1 unpadded generate, trimmed to the
    generated region (and through eos, which the engine includes)."""
    out = np.asarray(generate(model, params, jnp.asarray(prompt)[None],
                              max_new_tokens=max_new, **kw))
    toks = out[0, len(prompt):].tolist()
    eos = kw.get("eos_token_id")
    if eos is not None and eos in toks:
        toks = toks[:toks.index(eos) + 1]
    return toks


# ---- bucket ladder ------------------------------------------------------

def test_bucket_ladder_selection_and_padding():
    ladder = BucketLadder((8, 16, 32))
    assert ladder.bucket_for(1) == 8
    assert ladder.bucket_for(8) == 8
    assert ladder.bucket_for(9) == 16
    assert ladder.bucket_for(32) == 32
    assert ladder.bucket_for(33) is None  # reject, don't truncate
    ids, mask = ladder.pad_prompt([5, 6, 7], 8, pad_token_id=1)
    assert ids.tolist() == [1, 1, 1, 1, 1, 5, 6, 7]  # LEFT pad
    assert mask.tolist() == [0, 0, 0, 0, 0, 1, 1, 1]


def test_bucket_ladder_validation():
    with pytest.raises(ValueError):
        BucketLadder(())
    with pytest.raises(ValueError):
        BucketLadder((16, 8))
    with pytest.raises(ValueError):
        BucketLadder((8, 8))


# ---- greedy parity (the tentpole contract) ------------------------------

def test_greedy_parity_staggered_admission(tiny):
    """Requests admitted at different ticks, spanning both buckets and
    a slot-pool smaller than the request count, decode token-identical
    to sequential generate."""
    model, params = tiny
    prompts = _prompts((5, 11, 16, 7))
    refs = [_ref(model, params, p, 10) for p in prompts]
    eng = ContinuousBatchingEngine(
        model, params, EngineConfig(num_slots=2, buckets=(8, 16),
                                    max_new_tokens=10, max_queue=16))
    r0 = eng.submit(prompts[0])
    r1 = eng.submit(prompts[1])
    for _ in range(3):
        eng.step()
    r2 = eng.submit(prompts[2])
    r3 = eng.submit(prompts[3])
    eng.run_until_idle()
    for req, ref in zip((r0, r1, r2, r3), refs):
        assert req.tokens == ref
        assert req.state == "finished"
        assert req.finish_reason == "length"
        assert req.ttft_s is not None and req.ttft_s >= 0


def test_greedy_parity_with_eos(tiny):
    """eos mid-stream finishes the request early with identical tokens
    (eos included, as generate does before padding)."""
    model, params = tiny
    prompt = _prompts((9,), seed=3)[0]
    free_run = _ref(model, params, prompt, 12)
    eos = free_run[3]  # force an eos hit on the 4th generated token
    ref = _ref(model, params, prompt, 12, eos_token_id=eos)
    eng = ContinuousBatchingEngine(
        model, params, EngineConfig(num_slots=2, buckets=(16,),
                                    max_new_tokens=12, max_queue=4,
                                    eos_token_id=eos))
    req = eng.submit(prompt)
    eng.run_until_idle()
    assert req.tokens == ref
    assert req.tokens[-1] == eos
    assert req.finish_reason == "eos"


def test_greedy_parity_with_repetition_penalty(tiny):
    """The engine reuses apply_logits_controls with per-slot cursors —
    the penalized decode must still match sequential generate."""
    model, params = tiny
    prompts = _prompts((6, 13), seed=5)
    refs = [_ref(model, params, p, 8, repetition_penalty=1.5)
            for p in prompts]
    eng = ContinuousBatchingEngine(
        model, params, EngineConfig(num_slots=2, buckets=(8, 16),
                                    max_new_tokens=8, max_queue=4,
                                    repetition_penalty=1.5))
    outs = eng.generate_all(prompts)
    assert outs == refs


def test_decode_compiles_once_across_reclaim(tiny):
    """THE perf contract: one decode program for the whole engine
    lifetime — across staggered admission, slot reclaim, and both
    prefill buckets (which compile once each)."""
    model, params = tiny
    eng = ContinuousBatchingEngine(
        model, params, EngineConfig(num_slots=2, buckets=(8, 16),
                                    max_new_tokens=6, max_queue=16))
    if not hasattr(eng._decode_jit, "_cache_size"):
        pytest.skip("jit cache introspection unavailable")
    eng.warmup()
    prompts = _prompts((5, 11, 16, 7, 3, 9))
    reqs = [eng.submit(p) for p in prompts[:3]]
    for _ in range(4):
        eng.step()
    reqs += [eng.submit(p) for p in prompts[3:]]
    eng.run_until_idle()
    assert all(r.state == "finished" for r in reqs)
    assert eng._decode_jit._cache_size() == 1
    assert eng._prefill_jit._cache_size() == 2  # one per bucket
    assert eng._assign_jit._cache_size() == 1


# ---- scheduler fast lane ------------------------------------------------

def test_slot_reclaim_serves_queue_through_one_slot(tiny):
    model, params = tiny
    prompts = _prompts((5, 6, 7), seed=1)
    refs = [_ref(model, params, p, 5) for p in prompts]
    eng = ContinuousBatchingEngine(
        model, params, EngineConfig(num_slots=1, buckets=(8,),
                                    max_new_tokens=5, max_queue=8))
    reqs = [eng.submit(p) for p in prompts]
    eng.step()
    # one slot: exactly one running, rest queued
    assert [r.state for r in reqs].count("running") == 1
    eng.run_until_idle()
    assert [r.tokens for r in reqs] == refs
    stats = eng.stats()
    assert stats["completed"] == 3
    assert stats["prefills_per_bucket"] == {8: 3}


def test_queue_overflow_rejects_with_429_semantics(tiny):
    model, params = tiny
    eng = ContinuousBatchingEngine(
        model, params, EngineConfig(num_slots=1, buckets=(8,),
                                    max_new_tokens=4, max_queue=2))
    p = _prompts((4,))[0]
    eng.submit(p)
    eng.submit(p)
    with pytest.raises(QueueFull):
        eng.submit(p)
    assert eng.stats()["rejected_queue_full"] == 1
    assert eng.stats()["admitted"] == 2


def test_prompt_too_long_rejected(tiny):
    model, params = tiny
    eng = ContinuousBatchingEngine(
        model, params, EngineConfig(num_slots=1, buckets=(8, 16),
                                    max_new_tokens=4, max_queue=2))
    with pytest.raises(PromptTooLong):
        eng.submit(np.arange(1, 20, dtype=np.int32))  # > max bucket
    assert eng.stats()["rejected_prompt_too_long"] == 1


def test_no_headroom_rejected(tiny):
    """A bucket that fills max_position_embeddings leaves no room to
    decode — reject instead of silently clamping the cache write."""
    model, params = tiny
    eng = ContinuousBatchingEngine(
        model, params, EngineConfig(num_slots=1, buckets=(8, 64),
                                    max_new_tokens=4, max_queue=2))
    with pytest.raises(PromptTooLong):
        eng.submit(np.arange(1, 50, dtype=np.int32))  # bucket 64 == max


def test_cancel_queued_request(tiny):
    model, params = tiny
    eng = ContinuousBatchingEngine(
        model, params, EngineConfig(num_slots=1, buckets=(8,),
                                    max_new_tokens=4, max_queue=4))
    req = eng.submit(_prompts((4,))[0])
    assert eng.cancel(req.request_id) is True
    assert req.state == "cancelled"
    assert req.done
    assert eng.cancel("nonexistent") is False
    assert eng.stats()["cancelled"] == 1


def test_cancel_running_request_frees_slot(tiny):
    """Cancelling an in-flight request releases its lane to the next
    queued request at the following tick."""
    model, params = tiny
    prompts = _prompts((5, 6), seed=2)
    ref1 = _ref(model, params, prompts[1], 4)
    eng = ContinuousBatchingEngine(
        model, params, EngineConfig(num_slots=1, buckets=(8,),
                                    max_new_tokens=50, max_queue=4))
    r0 = eng.submit(prompts[0], max_new_tokens=50)
    r1 = eng.submit(prompts[1], max_new_tokens=4)
    eng.step()
    assert r0.state == "running" and r1.state == "queued"
    eng.cancel(r0.request_id)
    eng.run_until_idle()
    assert r0.state == "cancelled"
    assert r0.finish_reason == "cancelled"
    assert r1.state == "finished"
    assert r1.tokens == ref1  # reclaimed lane decodes untainted
    assert eng.stats()["cancelled"] == 1


def test_deadline_expires_queued_and_running(tiny):
    model, params = tiny
    now = [0.0]
    eng = ContinuousBatchingEngine(
        model, params, EngineConfig(num_slots=1, buckets=(8,),
                                    max_new_tokens=50, max_queue=4),
        clock=lambda: now[0])
    running = eng.submit(_prompts((5,))[0], deadline_s=10.0)
    queued = eng.submit(_prompts((6,))[0], deadline_s=1.0)
    eng.step()
    assert running.state == "running"
    now[0] = 5.0   # queued's deadline passed; running's has not
    eng.step()
    assert queued.state == "expired"
    assert running.state == "running"
    now[0] = 50.0
    eng.step()
    assert running.state == "expired"
    assert running.finish_reason == "deadline"
    assert eng.stats()["expired"] == 2


def test_ngram_blocklist_config_rejected(tiny):
    model, params = tiny
    with pytest.raises(ValueError, match="no_repeat_ngram_size"):
        ContinuousBatchingEngine(
            model, params, EngineConfig(no_repeat_ngram_size=2))


def test_background_thread_serving(tiny):
    """The API-layer mode: a daemon thread ticks the engine; submitters
    just wait on their request events."""
    model, params = tiny
    prompts = _prompts((5, 9, 14), seed=4)
    refs = [_ref(model, params, p, 6) for p in prompts]
    eng = ContinuousBatchingEngine(
        model, params, EngineConfig(num_slots=2, buckets=(8, 16),
                                    max_new_tokens=6, max_queue=8))
    eng.start()
    try:
        reqs = [eng.submit(p) for p in prompts]
        assert all(r.wait(timeout=60) for r in reqs)
        assert [r.tokens for r in reqs] == refs
    finally:
        eng.stop()


def test_engine_log_events(tiny):
    """Resilience-style structured log events (loader.py conventions)."""
    model, params = tiny
    events = []
    eng = ContinuousBatchingEngine(
        model, params, EngineConfig(num_slots=1, buckets=(8,),
                                    max_new_tokens=3, max_queue=2),
        log=events.append)
    eng.warmup()
    eng.generate_all(_prompts((4,)))
    kinds = [e["event"] for e in events]
    # engine startup states its kernel dispatch decision first
    # (docs/kernels.md), then warmup reports
    assert kinds[0] == "kernel_dispatch"
    assert kinds[1] == "serving_warmup"
    assert "serving_admit" in kinds
    assert "serving_finish" in kinds


def test_rollback_slots_per_lane(tiny):
    """The per-slot analog of _rollback_cache lowers each lane's write
    cursor independently."""
    from fengshen_tpu.serving import init_slot_cache
    from fengshen_tpu.utils.generate import is_cache_index_path
    model, _ = tiny
    cache = init_slot_cache(model, 3)
    cache = jax.tree_util.tree_map_with_path(
        lambda p, l: l + 7 if is_cache_index_path(p) else l, cache)
    rolled = rollback_slots(cache, jnp.asarray([1, 2, 3]))

    def check(path, leaf):
        if is_cache_index_path(path):
            np.testing.assert_array_equal(np.asarray(leaf), [6, 5, 4])
        return leaf
    jax.tree_util.tree_map_with_path(check, rolled)


# ---- API integration ----------------------------------------------------

class _FakeTokenizer:
    """Whitespace-int tokenizer: '5 7 9' <-> [5, 7, 9]."""

    eos_token_id = None
    pad_token_id = 0

    def encode(self, text):
        return [int(t) for t in text.split()]

    def decode(self, ids):
        return " ".join(str(int(t)) for t in ids)


def _gen_pipeline(tiny, **kw):
    from fengshen_tpu.pipelines.text_generation import Pipeline
    model, params = tiny
    return Pipeline(module=model, params=params,
                    tokenizer=_FakeTokenizer(), **kw)


def test_text_generation_pipeline_legacy_path(tiny):
    model, params = tiny
    pipe = _gen_pipeline(tiny, max_new_tokens=5)
    prompt = "5 7 9 11"
    ref = _ref(model, params, np.asarray([5, 7, 9, 11], np.int32), 5)
    assert pipe(prompt) == " ".join(str(t) for t in ref)


def test_api_stdlib_server_continuous_engine(tiny):
    """End-to-end: POST through the stdlib server is served by the
    engine thread; /stats exposes engine metrics; queue-full maps to
    429."""
    import json as json_mod
    import urllib.error
    import urllib.request

    from fengshen_tpu.api.main import (PipelineConfig, ServerConfig,
                                       build_stdlib_server,
                                       start_continuous_engine)

    model, params = tiny
    pipe = _gen_pipeline(tiny, max_new_tokens=5)
    engine = start_continuous_engine(
        pipe, {"num_slots": 2, "buckets": (8,), "max_queue": 8})
    server = build_stdlib_server(
        ServerConfig(host="127.0.0.1", port=0, engine="continuous"),
        PipelineConfig(task="text_generation"), pipeline=pipe,
        engine=engine)
    port = server.server_address[1]
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    try:
        ref = _ref(model, params, np.asarray([5, 7, 9], np.int32), 5)
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/api/text_generation",
            data=json_mod.dumps({"input_text": "5 7 9"}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as r:
            out = json_mod.loads(r.read())
        assert out["result"] == " ".join(str(t) for t in ref)
        assert out["finish_reason"] == "length"
        assert out["ttft_s"] >= 0
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/stats", timeout=10) as r:
            stats = json_mod.loads(r.read())
        assert stats["completed"] >= 1
        assert stats["num_slots"] == 2
        # prompt longer than every bucket → 413
        too_long = " ".join(["3"] * 12)
        bad = urllib.request.Request(
            f"http://127.0.0.1:{port}/api/text_generation",
            data=json_mod.dumps({"input_text": too_long}).encode(),
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(bad, timeout=60)
        assert exc.value.code == 413
    finally:
        server.shutdown()
        engine.stop()


def test_api_stdlib_server_queue_full_429(tiny):
    import json as json_mod
    import urllib.error
    import urllib.request

    from fengshen_tpu.api.main import (PipelineConfig, ServerConfig,
                                       build_stdlib_server)

    pipe = _gen_pipeline(tiny, max_new_tokens=4)
    # fill the 1-deep queue and start NO engine thread: nothing drains,
    # so the HTTP submit is deterministically backpressured
    eng = ContinuousBatchingEngine(
        pipe.module, pipe.params,
        EngineConfig(num_slots=1, buckets=(8,), max_new_tokens=4,
                     max_queue=1, pad_token_id=0))
    eng.submit(np.asarray([5, 7], np.int32))
    server = build_stdlib_server(
        ServerConfig(host="127.0.0.1", port=0, engine="continuous"),
        PipelineConfig(task="text_generation"), pipeline=pipe,
        engine=eng)
    port = server.server_address[1]
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/api/text_generation",
            data=json_mod.dumps({"input_text": "5 7"}).encode(),
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req, timeout=30)
        assert exc.value.code == 429
    finally:
        server.shutdown()


def test_warmup_pipeline_logs_seconds(tiny, capsys):
    from fengshen_tpu.api.main import warmup_pipeline

    calls = []

    def fake_pipeline(text):
        calls.append(text)
        return "ok"

    dt = warmup_pipeline(fake_pipeline, "text_generation")
    assert dt is not None and dt >= 0
    assert calls == ["warmup"]
    assert "compiled+ran" in capsys.readouterr().out

    def broken(text):
        raise RuntimeError("no params")

    assert warmup_pipeline(broken, "t") is None
    assert "warmup request failed" in capsys.readouterr().out


# ---- code-review hardening ----------------------------------------------

def test_submit_invalid_max_new_tokens(tiny):
    model, params = tiny
    eng = ContinuousBatchingEngine(
        model, params, EngineConfig(num_slots=1, buckets=(8,),
                                    max_new_tokens=4, max_queue=2))
    with pytest.raises(ValueError, match="max_new_tokens"):
        eng.submit(_prompts((4,))[0], max_new_tokens=0)
    # not a 413-class rejection: the prompt itself was fine
    assert eng.stats()["rejected_prompt_too_long"] == 0


def test_server_config_rejects_unknown_engine():
    from fengshen_tpu.api.main import ServerConfig
    with pytest.raises(ValueError, match="unknown engine"):
        ServerConfig(engine="continous")  # typo must fail at startup


def test_serve_loop_survives_tick_error(tiny):
    """A mid-tick exception must not leave waiters hanging for their
    full timeout: in-flight requests fail loudly with 'engine_error',
    the pool is rebuilt, and the NEXT request is served correctly."""
    model, params = tiny
    prompt = _prompts((5,), seed=9)[0]
    ref = _ref(model, params, prompt, 4)
    events = []
    eng = ContinuousBatchingEngine(
        model, params, EngineConfig(num_slots=1, buckets=(8,),
                                    max_new_tokens=4, max_queue=4),
        log=events.append)
    real_decode = eng._decode_jit
    boom = [True]

    def flaky(*args):
        if boom[0]:
            boom[0] = False
            raise RuntimeError("transient XLA failure")
        return real_decode(*args)

    eng._decode_jit = flaky
    eng.start()
    try:
        failed = eng.submit(prompt)
        assert failed.wait(timeout=60)
        assert failed.finish_reason == "engine_error"
        ok = eng.submit(prompt)
        assert ok.wait(timeout=60)
        assert ok.tokens == ref  # rebuilt pool decodes untainted
    finally:
        eng.stop()
    assert any(e["event"] == "serving_tick_error" for e in events)


def test_legacy_path_honors_max_new_tokens(tiny):
    """The simple engine must respect the per-request cap too."""
    import json as json_mod
    import urllib.request

    from fengshen_tpu.api.main import (PipelineConfig, ServerConfig,
                                       build_stdlib_server)

    model, params = tiny
    pipe = _gen_pipeline(tiny, max_new_tokens=6)
    ref = _ref(model, params, np.asarray([5, 7, 9], np.int32), 2)
    server = build_stdlib_server(
        ServerConfig(host="127.0.0.1", port=0),
        PipelineConfig(task="text_generation"), pipeline=pipe)
    port = server.server_address[1]
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/api/text_generation",
            data=json_mod.dumps({"input_text": "5 7 9",
                                 "max_new_tokens": 2}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as r:
            out = json_mod.loads(r.read())
        assert out["result"] == " ".join(str(t) for t in ref)
    finally:
        server.shutdown()


def test_engine_server_422_on_bad_max_new_tokens(tiny):
    import json as json_mod
    import urllib.error
    import urllib.request

    from fengshen_tpu.api.main import (PipelineConfig, ServerConfig,
                                       build_stdlib_server)

    pipe = _gen_pipeline(tiny, max_new_tokens=4)
    eng = ContinuousBatchingEngine(
        pipe.module, pipe.params,
        EngineConfig(num_slots=1, buckets=(8,), max_new_tokens=4,
                     max_queue=4))
    server = build_stdlib_server(
        ServerConfig(host="127.0.0.1", port=0, engine="continuous"),
        PipelineConfig(task="text_generation"), pipeline=pipe,
        engine=eng)
    port = server.server_address[1]
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/api/text_generation",
            data=json_mod.dumps({"input_text": "5 7",
                                 "max_new_tokens": 0}).encode(),
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req, timeout=30)
        assert exc.value.code == 422
    finally:
        server.shutdown()


def test_engine_config_rejects_zero_queue(tiny):
    with pytest.raises(ValueError, match="max_queue"):
        EngineConfig(max_queue=0)


def test_pipeline_honors_cli_args(tiny):
    """fengshen-pipeline parses flags into `args`; the pipeline must
    read them, not silently fall back to its defaults."""
    import argparse

    from fengshen_tpu.pipelines.text_generation import Pipeline

    parser = argparse.ArgumentParser()
    Pipeline.add_pipeline_specific_args(parser)
    args = parser.parse_args(["--max_new_tokens", "3",
                              "--temperature", "0.7"])
    model, params = tiny
    pipe = Pipeline(args=args, module=model, params=params,
                    tokenizer=_FakeTokenizer())
    assert pipe.max_new_tokens == 3
    assert pipe.sample_kw["temperature"] == 0.7
    assert len(pipe("5 7 9").split()) == 3
