"""Checkpoint round-trip, generation parity, and collator-stack tests."""

import argparse
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest


# -- checkpoint ----------------------------------------------------------

def test_checkpoint_save_restore_roundtrip(tmp_path, mesh8):
    import optax
    from fengshen_tpu.trainer.train_state import TrainState
    from fengshen_tpu.utils.universal_checkpoint import UniversalCheckpoint

    params = {"w": jnp.arange(16.0).reshape(4, 4), "b": jnp.ones((4,))}
    tx = optax.adamw(1e-3)
    state = TrainState.create(apply_fn=lambda: None, params=params, tx=tx)
    state = state.apply_gradients(
        jax.tree_util.tree_map(jnp.ones_like, params))

    parser = argparse.ArgumentParser()
    UniversalCheckpoint.add_argparse_args(parser)
    args = parser.parse_args(["--save_ckpt_path", str(tmp_path / "ck"),
                              "--load_ckpt_path", str(tmp_path / "ck")])

    class FakeTrainer:
        global_step = 7
        consumed_samples = 700

    cb = UniversalCheckpoint(args)
    cb.save(state, FakeTrainer())

    fresh = TrainState.create(apply_fn=lambda: None,
                              params=jax.tree_util.tree_map(
                                  jnp.zeros_like, params), tx=tx)
    t2 = FakeTrainer()
    t2.global_step = 0
    t2.consumed_samples = 0
    restored = cb.maybe_restore(fresh, t2)
    np.testing.assert_allclose(restored.params["w"], state.params["w"])
    assert t2.global_step == 7 and t2.consumed_samples == 700
    assert int(restored.step) == 7


def test_checkpoint_weights_only_restore_into_full_run(tmp_path, mesh8):
    """A --save_weights_only checkpoint restored by a run WITHOUT that flag
    must silently keep the fresh optimizer state (ADVICE r1)."""
    import optax
    from fengshen_tpu.trainer.train_state import TrainState
    from fengshen_tpu.utils.universal_checkpoint import UniversalCheckpoint

    params = {"w": jnp.arange(16.0).reshape(4, 4), "b": jnp.ones((4,))}
    tx = optax.adamw(1e-3)
    state = TrainState.create(apply_fn=lambda: None, params=params, tx=tx)

    parser = argparse.ArgumentParser()
    UniversalCheckpoint.add_argparse_args(parser)
    save_args = parser.parse_args(
        ["--save_ckpt_path", str(tmp_path / "ck"),
         "--load_ckpt_path", str(tmp_path / "ck"), "--save_weights_only"])

    class FakeTrainer:
        global_step = 3
        consumed_samples = 30

    UniversalCheckpoint(save_args).save(state, FakeTrainer())

    load_args = parser.parse_args(
        ["--save_ckpt_path", str(tmp_path / "ck"),
         "--load_ckpt_path", str(tmp_path / "ck")])  # full run, no flag
    fresh = TrainState.create(apply_fn=lambda: None,
                              params=jax.tree_util.tree_map(
                                  jnp.zeros_like, params), tx=tx)
    t2 = FakeTrainer()
    restored = UniversalCheckpoint(load_args).maybe_restore(fresh, t2)
    np.testing.assert_allclose(restored.params["w"], state.params["w"])
    # optimizer state falls back to the freshly initialized one
    chex = __import__("chex")
    chex.assert_trees_all_equal(restored.opt_state, fresh.opt_state)


def test_checkpoint_missing_load_path_silently_skipped(tmp_path):
    import optax
    from fengshen_tpu.trainer.train_state import TrainState
    from fengshen_tpu.utils.universal_checkpoint import UniversalCheckpoint
    parser = argparse.ArgumentParser()
    UniversalCheckpoint.add_argparse_args(parser)
    args = parser.parse_args(["--load_ckpt_path",
                              str(tmp_path / "missing")])
    state = TrainState.create(apply_fn=lambda: None,
                              params={"w": jnp.ones((2,))},
                              tx=optax.sgd(1e-3))
    cb = UniversalCheckpoint(args)

    class T:
        global_step = 0
        consumed_samples = 0

    out = cb.maybe_restore(state, T())
    assert out is state  # reference behaviour: drop missing path silently


# -- generation ----------------------------------------------------------

def test_greedy_generate_matches_hf():
    torch = pytest.importorskip("torch")
    import transformers
    from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from fengshen_tpu.models.llama.convert import torch_to_params
    from fengshen_tpu.utils.generate import generate

    hf_cfg = transformers.LlamaConfig(
        vocab_size=96, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=4,
        max_position_embeddings=64, attn_implementation="eager",
        tie_word_embeddings=False)
    torch.manual_seed(3)
    tm = transformers.LlamaForCausalLM(hf_cfg).eval()
    cfg = LlamaConfig(vocab_size=96, hidden_size=32, intermediate_size=64,
                      num_hidden_layers=2, num_attention_heads=4,
                      max_position_embeddings=64, dtype="float32")
    params = torch_to_params(tm.state_dict(), cfg)
    model = LlamaForCausalLM(cfg)

    prompt = np.array([[5, 11, 42, 7]], dtype=np.int64)
    with torch.no_grad():
        ref = tm.generate(torch.tensor(prompt), max_new_tokens=8,
                          do_sample=False).numpy()
    out = generate(model, params, jnp.asarray(prompt, jnp.int32),
                   max_new_tokens=8)
    np.testing.assert_array_equal(np.asarray(out)[0], ref[0])


def test_generate_left_padded_batch():
    from fengshen_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from fengshen_tpu.utils.generate import generate

    cfg = LlamaConfig.small_test_config(dtype="float32")
    model = LlamaForCausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]

    # single sequence vs the same sequence left-padded in a batch
    seq = np.array([9, 4, 77, 31], dtype=np.int32)
    single = generate(model, params, jnp.asarray(seq[None]),
                      max_new_tokens=4)
    padded = np.concatenate([[0, 0], seq]).astype(np.int32)
    mask = np.array([[0, 0, 1, 1, 1, 1]], dtype=np.int32)
    batch = generate(model, params, jnp.asarray(padded[None]),
                     attention_mask=jnp.asarray(mask), max_new_tokens=4)
    np.testing.assert_array_equal(np.asarray(batch)[0, -4:],
                                  np.asarray(single)[0, -4:])


def test_top_k_top_p_filters():
    from fengshen_tpu.utils.generate import top_k_logits, top_p_logits
    logits = jnp.asarray([[1.0, 2.0, 3.0, 4.0]])
    k2 = top_k_logits(logits, k=2)
    assert np.asarray(k2)[0, 0] < -1e8 and np.asarray(k2)[0, 1] < -1e8
    assert np.asarray(k2)[0, 3] == 4.0
    # p small → only the top token survives
    p = top_p_logits(jnp.asarray([[0.0, 0.0, 5.0, 0.0]]), p=0.1)
    kept = np.asarray(p)[0] > -1e8
    assert kept.tolist() == [False, False, True, False]


# -- collator stack -------------------------------------------------------

def test_sentence_split():
    from fengshen_tpu.data.data_utils import ChineseSentenceSplitter
    s = ChineseSentenceSplitter()
    out = s.tokenize("今天天气很好。我们去公园吧！好吗？然后回家")
    assert out == ["今天天气很好。", "我们去公园吧！", "好吗？", "然后回家"]


def test_sop_pairing():
    from fengshen_tpu.data.data_utils import get_a_and_b_segments
    rng = np.random.RandomState(0)
    sents = [[1, 2], [3, 4], [5, 6]]
    a, b, swapped = get_a_and_b_segments(sents, rng)
    assert sorted(a + b) == [1, 2, 3, 4, 5, 6]
    if not swapped:
        assert a[0] == 1
    else:
        assert b[0] == 1


def test_truncate_segments():
    from fengshen_tpu.data.data_utils import truncate_segments
    rng = np.random.RandomState(1)
    a, b = list(range(10)), list(range(10, 18))
    truncated = truncate_segments(a, b, len(a), len(b), 12, rng)
    assert truncated and len(a) + len(b) == 12


def test_tokens_and_tokentypes():
    from fengshen_tpu.data.data_utils import create_tokens_and_tokentypes
    toks, types = create_tokens_and_tokentypes([5, 6], [7], cls_id=1,
                                               sep_id=2)
    assert toks == [1, 5, 6, 2, 7, 2]
    assert types == [0, 0, 0, 0, 1, 1]


def test_masked_lm_predictions_bert():
    from fengshen_tpu.data.data_utils import create_masked_lm_predictions
    vocab = {i: f"tok{i}" for i in range(100)}
    vocab[1], vocab[2], vocab[3] = "[CLS]", "[SEP]", "[MASK]"
    tokens = [1] + list(range(10, 30)) + [2]
    rng = np.random.RandomState(0)
    out, positions, labels = create_masked_lm_predictions(
        tokens, list(vocab), vocab, masked_lm_prob=0.3, cls_id=1, sep_id=2,
        mask_id=3, max_predictions_per_seq=6, np_rng=rng)
    assert len(positions) == len(labels) > 0
    assert 0 not in positions and len(tokens) - 1 not in positions
    for pos, label in zip(positions, labels):
        assert tokens[pos] == label  # label is the original token
    assert len(out) == len(tokens)


def test_masked_lm_whole_word_jieba():
    jieba = pytest.importorskip("jieba")
    from fengshen_tpu.data.data_utils.mask_utils import whole_word_spans
    chars = list("我们喜欢机器学习")
    spans = whole_word_spans(chars, zh_tokenizer=jieba.lcut)
    # jieba groups 我们/喜欢/机器/学习 (or similar multi-char words)
    assert sum(len(s) for s in spans) == len(chars)
    assert any(len(s) > 1 for s in spans)


def test_chinese_char_tokenize():
    from fengshen_tpu.utils import chinese_char_tokenize, is_chinese_char
    assert is_chinese_char(ord("中"))
    assert not is_chinese_char(ord("a"))
    assert chinese_char_tokenize("ab中c").split() == ["ab", "中", "c"]


def test_delta_roundtrip():
    from fengshen_tpu.utils.delta import make_delta, apply_delta
    base = {"w": np.ones((4,)), "b": np.zeros((2,))}
    target = {"w": np.full((4,), 3.0), "b": np.ones((2,))}
    delta = make_delta(base, target)
    back = apply_delta(base, delta)
    np.testing.assert_allclose(back["w"], target["w"])
    np.testing.assert_allclose(back["b"], target["b"])


def test_report_memory_runs(capsys):
    from fengshen_tpu.utils.utils import report_memory
    stats = report_memory("test")
    assert len(stats) >= 1
    assert "report_memory" in capsys.readouterr().out


def test_mmap_index_dataset(tmp_path):
    from fengshen_tpu.data.mmap_dataloader.mmap_index_dataset import (
        MMapIndexDataset, convert_py_to_npy)
    rows = [[1, 2, 3], [4, 5], [6]]
    convert_py_to_npy(rows, str(tmp_path), "input_ids")
    ds = MMapIndexDataset(str(tmp_path), ["input_ids"])
    assert len(ds) == 3
    np.testing.assert_array_equal(ds[0]["input_ids"], [1, 2, 3])
    np.testing.assert_array_equal(ds[2]["input_ids"], [6])


def test_conll_loader(tmp_path):
    from fengshen_tpu.data.sequence_tagging_dataloader import load_conll
    p = tmp_path / "ner.txt"
    p.write_text("北 B-LOC\n京 I-LOC\n好 O\n\n天 O\n")
    samples = load_conll(str(p))
    assert samples[0]["text"] == "北京好"
    assert samples[0]["labels"] == ["B-LOC", "I-LOC", "O"]
    assert samples[1]["text"] == "天"


def test_task_datasets(tmp_path):
    from fengshen_tpu.data.task_dataloader import (LCSTSDataset,
                                                   MedicalQADataset)
    p = tmp_path / "lcsts.jsonl"
    p.write_text('{"text": "正文", "summary": "摘要"}\n')
    ds = LCSTSDataset(str(p))
    assert ds[0] == {"text": "正文", "summary": "摘要"}
    q = tmp_path / "qa.jsonl"
    q.write_text('{"question": "问", "answer": "答"}\n')
    qa = MedicalQADataset(str(q))
    assert qa[0] == {"question": "问", "answer": "答"}
