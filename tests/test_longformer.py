"""Longformer behavioural tests: window locality + global reach."""

import jax
import jax.numpy as jnp
import numpy as np

from fengshen_tpu.models.longformer import (LongformerConfig,
                                            LongformerModel)


def _setup():
    cfg = LongformerConfig.small_test_config(dtype="float32")
    model = LongformerModel(cfg, add_pooling_layer=False)
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 127, (1, 32)),
                      jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids)["params"]
    return cfg, model, ids, params


def test_window_locality():
    cfg, model, ids, params = _setup()
    out, _ = model.apply({"params": params}, ids)
    # perturb token 31; token 0 is far outside every layer-hop window
    ids2 = ids.at[0, 31].set((int(ids[0, 31]) + 1) % 127)
    out2, _ = model.apply({"params": params}, ids2)
    # receptive field after 2 layers = 2*half = 8 positions; token 0
    # cannot see position 31
    np.testing.assert_allclose(np.asarray(out[0, 0]),
                               np.asarray(out2[0, 0]), atol=1e-5)
    # but token 28 (within window of 31) must change
    assert float(jnp.abs(out[0, 28] - out2[0, 28]).max()) > 1e-6


def test_global_attention_reaches_everywhere():
    cfg, model, ids, params = _setup()
    gmask = jnp.zeros((1, 32), jnp.int32).at[0, 0].set(1)
    out, _ = model.apply({"params": params}, ids,
                         global_attention_mask=gmask)
    ids2 = ids.at[0, 31].set((int(ids[0, 31]) + 1) % 127)
    out2, _ = model.apply({"params": params}, ids2,
                          global_attention_mask=gmask)
    # global token 0 sees position 31
    assert float(jnp.abs(out[0, 0] - out2[0, 0]).max()) > 1e-6


def test_rotary_variant_runs():
    cfg = LongformerConfig.small_test_config(dtype="float32",
                                             use_rotary=True)
    model = LongformerModel(cfg, add_pooling_layer=False)
    ids = jnp.asarray(np.random.RandomState(1).randint(0, 127, (1, 16)),
                      jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids)["params"]
    out, _ = model.apply({"params": params}, ids)
    assert np.isfinite(np.asarray(out)).all()
    # no learned position table in the rotary variant
    assert "position_embeddings" not in params
