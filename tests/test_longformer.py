"""Longformer behavioural tests: window locality + global reach."""

import jax
import jax.numpy as jnp
import numpy as np

from fengshen_tpu.models.longformer import (LongformerConfig,
                                            LongformerModel)


def _setup():
    cfg = LongformerConfig.small_test_config(dtype="float32")
    model = LongformerModel(cfg, add_pooling_layer=False)
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 127, (1, 32)),
                      jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids)["params"]
    return cfg, model, ids, params


def test_window_locality():
    cfg, model, ids, params = _setup()
    out, _ = model.apply({"params": params}, ids)
    # perturb token 31; token 0 is far outside every layer-hop window
    ids2 = ids.at[0, 31].set((int(ids[0, 31]) + 1) % 127)
    out2, _ = model.apply({"params": params}, ids2)
    # receptive field after 2 layers = 2*half = 8 positions; token 0
    # cannot see position 31
    np.testing.assert_allclose(np.asarray(out[0, 0]),
                               np.asarray(out2[0, 0]), atol=1e-5)
    # but token 28 (within window of 31) must change
    assert float(jnp.abs(out[0, 28] - out2[0, 28]).max()) > 1e-6


def test_global_attention_reaches_everywhere():
    cfg, model, ids, params = _setup()
    gmask = jnp.zeros((1, 32), jnp.int32).at[0, 0].set(1)
    out, _ = model.apply({"params": params}, ids,
                         global_attention_mask=gmask)
    ids2 = ids.at[0, 31].set((int(ids[0, 31]) + 1) % 127)
    out2, _ = model.apply({"params": params}, ids2,
                          global_attention_mask=gmask)
    # global token 0 sees position 31
    assert float(jnp.abs(out[0, 0] - out2[0, 0]).max()) > 1e-6


def test_rotary_variant_runs():
    cfg = LongformerConfig.small_test_config(dtype="float32",
                                             use_rotary=True)
    model = LongformerModel(cfg, add_pooling_layer=False)
    ids = jnp.asarray(np.random.RandomState(1).randint(0, 127, (1, 16)),
                      jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids)["params"]
    out, _ = model.apply({"params": params}, ids)
    assert np.isfinite(np.asarray(out)).all()
    # no learned position table in the rotary variant
    assert "position_embeddings" not in params


def test_banded_attention_matches_dense_reference():
    """The chunked O(S·w) attention must equal a dense-with-mask oracle:
    local window ∪ global columns (local proj) for local rows, full
    attention (global proj) for global rows."""
    import flax.linen as fnn
    from fengshen_tpu.models.longformer.modeling_longformer import (
        LongformerConfig, LongformerSelfAttention)

    cfg = LongformerConfig.small_test_config(
        attention_window=8, max_global_tokens=4, dtype="float32")
    batch, seq = 2, 37  # deliberately not a multiple of the chunk size
    rng = np.random.RandomState(0)
    hidden = jnp.asarray(rng.randn(batch, seq, cfg.hidden_size), jnp.float32)
    mask = np.ones((batch, seq), np.int32)
    mask[1, 30:] = 0
    gmask = np.zeros((batch, seq), np.int32)
    gmask[:, 0] = 1
    gmask[0, 5] = 1

    attn = LongformerSelfAttention(cfg)
    params = attn.init(jax.random.PRNGKey(0), hidden)
    out = attn.apply(params, hidden, jnp.asarray(mask), jnp.asarray(gmask))

    # dense oracle with the same parameters
    p = params["params"]

    def proj(name, rot=False):
        w, b = p[name]["kernel"], p[name]["bias"]
        x = hidden @ w + b
        return x.reshape(batch, seq, cfg.num_attention_heads, cfg.head_dim)

    q, k, v = proj("query"), proj("key"), proj("value")
    qg, kg, vg = proj("query_global"), proj("key_global"), proj("value_global")
    half = cfg.attention_window // 2
    pos = np.arange(seq)
    local = np.abs(pos[:, None] - pos[None, :]) <= half
    valid = mask.astype(bool)
    is_global = gmask.astype(bool) & valid
    allowed = (local[None] | is_global[:, None, :]) & valid[:, None, :]
    scale = 1.0 / np.sqrt(cfg.head_dim)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    scores = jnp.where(jnp.asarray(allowed)[:, None], scores, -1e9)
    probs = jax.nn.softmax(scores, -1)
    out_local = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
    g_scores = jnp.einsum("bqhd,bkhd->bhqk", qg, kg) * scale
    g_scores = jnp.where(jnp.asarray(valid)[:, None, None, :], g_scores, -1e9)
    out_glob = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(g_scores, -1), vg)
    ref = jnp.where(jnp.asarray(is_global)[:, :, None, None],
                    out_glob, out_local)
    ref = ref.reshape(batch, seq, cfg.hidden_size)

    valid_rows = np.asarray(valid)
    np.testing.assert_allclose(np.asarray(out)[valid_rows],
                               np.asarray(ref)[valid_rows], atol=2e-4)
