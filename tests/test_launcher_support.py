"""Driver surface added for the round-4 launcher matrix (VERDICT r3
missing #1/#3): rouge metric, clue predict2submit, summary eval path,
llama convert CLI."""

import json
import os

import numpy as np
import pytest


# -- rouge ----------------------------------------------------------------

def test_rouge_hand_computed():
    from fengshen_tpu.metrics.rouge import rouge_l, rouge_n

    pred, ref = "a b c", "a c d"
    # unigrams: match {a, c} = 2, P=2/3, R=2/3 → F=2/3
    assert abs(rouge_n(pred, ref, 1) - 2 / 3) < 1e-9
    # bigrams: {ab, bc} vs {ac, cd} → 0
    assert rouge_n(pred, ref, 2) == 0.0
    # LCS "a c" = 2 → F=2/3
    assert abs(rouge_l(pred, ref) - 2 / 3) < 1e-9
    assert rouge_l("x", "") == 0.0


def test_rouge_chinese_char_level():
    from fengshen_tpu.metrics.rouge import rouge_scores

    scores = rouge_scores(["今天天气好"], ["今天天气好"], char_level=True)
    assert scores["rouge1_fmeasure"] == 1.0
    assert scores["rougeL_fmeasure"] == 1.0
    partial = rouge_scores(["今天很好"], ["今天天气好"], char_level=True)
    assert 0.0 < partial["rouge1_fmeasure"] < 1.0


# -- predict2submit -------------------------------------------------------

from fengshen_tpu.examples.clue1_1 import predict2submit as p2s


def test_submit_afqmc_and_ocnli():
    rows = [{"id": 1, "label": 0}, {"id": 2, "label": 1}]
    assert p2s.submit_afqmc(rows) == [{"id": 1, "label": "0"},
                                      {"id": 2, "label": "1"}]
    rows3 = [{"id": 5, "label": 2}]
    assert p2s.submit_ocnli(rows3) == [{"id": 5, "label": "entailment"}]


def test_submit_tnews_desc_to_code():
    rows = [{"id": 0, "choice": ["故事", "文化"], "label": 1}]
    assert p2s.submit_tnews(rows) == [{"id": 0, "label": "101"}]


def test_submit_wsc_option_order():
    # reference: wsc_submit.py:8-21 — mapping flips with option order
    rows = [{"id": 0, "choice": ["他不是指小明", "他是指小明"], "label": 1},
            {"id": 1, "choice": ["他是指小明", "他不是指小明"], "label": 1}]
    out = p2s.submit_wsc(rows)
    assert out[0]["label"] == "false"
    assert out[1]["label"] == "false"
    rows2 = [{"id": 2, "choice": ["他不是指小明", "他是指小明"], "label": 0}]
    assert p2s.submit_wsc(rows2)[0]["label"] == "true"


def test_submit_csl_groups_higher_half():
    # one abstract, two keyword rows: higher-scored row → class 0 → '1'
    rows = [{"id": 10, "texta": "T", "choice": ["可以"],
             "score": {"可以": 0.9}},
            {"id": 11, "texta": "T", "choice": ["可以"],
             "score": {"可以": 0.1}}]
    out = {r["id"]: r["label"] for r in p2s.submit_csl(rows)}
    assert out == {10: "1", 11: "0"}


def test_submit_chid_exclusive_assignment():
    # two blanks in one group, same favourite option: the lower-scored
    # row must take its second choice (reference recls semantics)
    rows = [{"id": "#idiom1#", "line_id": 7,
             "score": {"a": 0.9, "b": 0.5}},
            {"id": "#idiom2#", "line_id": 7,
             "score": {"a": 0.8, "b": 0.1}}]
    out = p2s.submit_chid(rows)
    assert out["#idiom1#"] == 0 and out["#idiom2#"] == 1


def test_submit_cmrc2018_best_span():
    rows = [{"choices": [
        {"id": "q1", "entity_list": [
            {"entity_name": "北京", "score": 0.4},
            {"entity_name": "上海", "score": 0.9}]},
        {"id": "q2", "entity_list": []}]}]
    out = p2s.submit_cmrc2018(rows)
    assert out == {"q1": "上海", "q2": ""}


def test_submit_iflytek_label_map(tmp_path):
    rows = [{"id": 3, "choice": ["打车", "地图"], "label": 1}]
    label_map = {"0": "打车", "1": "地图"}
    out = p2s.submit_iflytek(rows, label_map)
    assert out == [{"id": 3, "label": "1"}]


def test_predict2submit_cli(tmp_path):
    pred = tmp_path / "afqmc_predict.json"
    with open(pred, "w") as f:
        f.write(json.dumps({"id": 1, "label": 1}) + "\n")
    out = tmp_path / "submit.json"
    p2s.main(["--task", "afqmc", "--data_path", str(pred),
              "--save_path", str(out)])
    assert json.loads(out.read_text())["label"] == "1"


# -- llama convert CLI ----------------------------------------------------

@pytest.mark.slow
def test_llama_convert_cli(tmp_path):
    import torch

    from fengshen_tpu.models.llama.configuration_llama import LlamaConfig

    cfg = LlamaConfig(vocab_size=32, hidden_size=16, intermediate_size=32,
                      num_hidden_layers=1, num_attention_heads=4,
                      num_key_value_heads=4)
    src = tmp_path / "hf"
    src.mkdir()
    cfg.save_pretrained(str(src))
    hd = cfg.hidden_size
    state = {"model.embed_tokens.weight": torch.randn(32, hd),
             "model.norm.weight": torch.ones(hd),
             "lm_head.weight": torch.randn(32, hd)}
    pre = "model.layers.0"
    for proj in ("q_proj", "k_proj", "v_proj", "o_proj"):
        state[f"{pre}.self_attn.{proj}.weight"] = torch.randn(hd, hd)
    for proj, shape in (("gate_proj", (32, hd)), ("up_proj", (32, hd)),
                        ("down_proj", (hd, 32))):
        state[f"{pre}.mlp.{proj}.weight"] = torch.randn(*shape)
    state[f"{pre}.input_layernorm.weight"] = torch.ones(hd)
    state[f"{pre}.post_attention_layernorm.weight"] = torch.ones(hd)
    torch.save(state, str(src / "pytorch_model.bin"))

    from fengshen_tpu.models.llama import convert as llama_convert
    out = tmp_path / "fs"
    llama_convert.main(["--input_path", str(src),
                        "--output_path", str(out),
                        "--model_parallel_size", "4"])
    assert (out / "config.json").exists()
    assert (out / "params").exists()
    meta = json.loads((out / "parallel_meta.json").read_text())
    assert meta["intended_model_parallel_size"] == 4
    # non-divisible TP must fail loudly
    with pytest.raises(ValueError):
        llama_convert.save_converted(
            str(tmp_path / "bad"), cfg, {}, model_parallel_size=3)


# -- summary eval path ----------------------------------------------------

@pytest.mark.slow
def test_summary_do_eval_only(tmp_path, mesh8, monkeypatch):
    """--do_eval_only: restore-free predict + rouge report + predictions
    file (the randeng_t5_70M_summary_predict.sh path)."""
    monkeypatch.chdir(tmp_path)
    from transformers import BertTokenizer

    chars = list("今天天气很好糟糕新闻摘要内容标题经济体育")
    vocab = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"] + \
        sorted(set(chars))
    (tmp_path / "vocab.txt").write_text("\n".join(vocab))
    model_dir = tmp_path / "model"
    model_dir.mkdir()
    BertTokenizer(str(tmp_path / "vocab.txt")).save_pretrained(
        str(model_dir))
    with open(model_dir / "config.json", "w") as f:
        json.dump({"model_type": "t5", "vocab_size": len(vocab),
                   "d_model": 32, "d_kv": 8, "d_ff": 64, "num_layers": 2,
                   "num_heads": 4, "dtype": "float32"}, f)
    rng = np.random.RandomState(0)
    for name in ("train.json", "test.json"):
        with open(tmp_path / name, "w") as f:
            for i in range(4):
                f.write(json.dumps(
                    {"text": "".join(rng.choice(chars, 10)),
                     "summary": "".join(rng.choice(chars, 4))},
                    ensure_ascii=False) + "\n")

    from fengshen_tpu.examples.summary import seq2seq_summary
    out = tmp_path / "predict.json"
    seq2seq_summary.main([
        "--model_type", "t5",
        "--model_path", str(model_dir),
        "--do_eval_only",
        "--output_save_path", str(out),
        "--train_file", str(tmp_path / "train.json"),
        "--test_file", str(tmp_path / "test.json"),
        "--test_batchsize", "2",
        "--max_enc_length", "16", "--max_dec_length", "8",
        "--prompt", "摘要:",
        "--default_root_dir", str(tmp_path / "runs"),
        "--save_ckpt_path", str(tmp_path / "ckpt"),
        "--load_ckpt_path", str(tmp_path / "ckpt"),
        "--precision", "fp32",
    ])
    lines = [json.loads(x) for x in open(out, encoding="utf-8")]
    assert len(lines) == 4 and all("pred" in r for r in lines)
