"""Multimodal serving engines (serving/multimodal.py) and the api
engine-type dispatch (docs/serving.md "Multimodal engines").

The contracts pinned here:

- `MicroBatchEngine` actually micro-batches (requests inside one gather
  window ride one `run_batch` launch) and honors the continuous
  engine's admission surface — QueueFull, Draining, DuplicateRequest —
  so the fleet router's retry contract holds across engine types;
- `_multimodal_generate` maps those to the same HTTP codes the text
  path uses (429/503/409/422) and the 200 body carries `engine_type`;
- both server paths (stdlib + fastapi, when installed) dispatch on
  `engine.engine_type` — a batch_image/embedding engine behind
  `POST /api/<task>` answers through the micro-batch path, and `/stats`
  exposes the micro-batch block;
- the `make serve-bench-multimodal` harness emits one BENCH-schema row
  per engine type, each carrying `engine_type` (benchdiff folds it
  into the row identity).

The engine/dispatch unit tests run on a fake pipeline so the machinery
is pinned fast and deterministically; the real towers (small-test
Taiyi-SD denoise loop + VAE decode, Taiyi-CLIP text embeddings) are
exercised end-to-end — pipeline → engine → stdlib HTTP server — by the
tests at the bottom, and through the bench harness smoke.
"""

import io
import json
import os
import threading
import time
from contextlib import redirect_stdout

import pytest

from fengshen_tpu.serving import (Draining, DuplicateRequest, QueueFull,
                                  BatchImageEngine, EmbeddingEngine,
                                  MULTIMODAL_ENGINE_TYPES,
                                  create_multimodal_engine)
from fengshen_tpu.serving.multimodal import (MM_CANCELLED, MM_FAILED,
                                             MM_FINISHED, MM_QUEUED)


class FakePipeline:
    """Stands in for pipelines/{image_generation,embedding}: records
    the batches the engine launches."""

    def __init__(self, fail=False, delay_s=0.0):
        self.batches = []
        self.fail = fail
        self.delay_s = delay_s

    def warmup_input(self):
        return "warmup"

    def run_batch(self, inputs):
        if self.delay_s:
            time.sleep(self.delay_s)
        self.batches.append(list(inputs))
        if self.fail:
            raise RuntimeError("tower exploded")
        return [{"result_for": text} for text in inputs]


def _engine(cls=EmbeddingEngine, pipeline=None, **kw):
    kw.setdefault("gather_ms", 20.0)
    eng = cls(pipeline if pipeline is not None else FakePipeline(), **kw)
    return eng


def test_engine_requires_run_batch_pipeline():
    class TextPipeline:
        def __call__(self, text):
            return text

    with pytest.raises(ValueError, match="run_batch"):
        EmbeddingEngine(TextPipeline())


def test_create_multimodal_engine_table():
    assert set(MULTIMODAL_ENGINE_TYPES) == {"batch_image", "embedding"}
    pipe = FakePipeline()
    eng = create_multimodal_engine("batch_image", pipe,
                                   {"max_batch": 3, "gather_ms": 0.0})
    assert isinstance(eng, BatchImageEngine)
    assert eng.engine_type == "batch_image"
    assert eng.max_batch == 3 and eng.gather_ms == 0.0
    with pytest.raises(ValueError, match="unknown multimodal engine"):
        create_multimodal_engine("continuous", pipe)


def test_submit_wait_finish_roundtrip():
    pipe = FakePipeline()
    eng = _engine(pipeline=pipe)
    eng.start()
    try:
        req = eng.submit("你好")
        assert req.wait(timeout=10)
        assert req.state == MM_FINISHED
        assert req.result == {"result_for": "你好"}
        assert req.request_id.startswith("embedding-")
    finally:
        eng.stop()
    assert eng.idle()


def test_requests_in_gather_window_ride_one_batch():
    pipe = FakePipeline()
    eng = _engine(pipeline=pipe, max_batch=4, gather_ms=200.0)
    reqs = [eng.submit(f"p{i}") for i in range(3)]
    eng.start()
    try:
        for r in reqs:
            assert r.wait(timeout=10) and r.state == MM_FINISHED
    finally:
        eng.stop()
    assert pipe.batches == [["p0", "p1", "p2"]]
    stats = eng.stats()
    assert stats["batches_total"] == 1
    assert stats["avg_batch"] == 3.0


def test_batch_never_exceeds_max_batch():
    pipe = FakePipeline()
    eng = _engine(pipeline=pipe, max_batch=2, gather_ms=50.0)
    reqs = [eng.submit(f"p{i}") for i in range(5)]
    eng.start()
    try:
        for r in reqs:
            assert r.wait(timeout=10) and r.state == MM_FINISHED
    finally:
        eng.stop()
    assert all(len(b) <= 2 for b in pipe.batches)
    assert sum(len(b) for b in pipe.batches) == 5


def test_admission_contract_queue_full_duplicate_drain():
    eng = _engine(max_queue=2)  # worker NOT started: nothing drains
    eng.submit("a", request_id="r1")
    with pytest.raises(DuplicateRequest):
        eng.submit("a again", request_id="r1")
    eng.submit("b")
    with pytest.raises(QueueFull):
        eng.submit("c")
    with pytest.raises(ValueError, match="empty input"):
        eng.submit("   ")
    eng.begin_drain()
    with pytest.raises(Draining):
        eng.submit("d", request_id="r9")
    assert eng.stats()["draining"] is True


def test_cancel_queued_request():
    eng = _engine()
    req = eng.submit("a", request_id="doomed")
    assert eng.cancel("doomed") is True
    assert req.state == MM_CANCELLED
    assert eng.cancel("doomed") is False        # already gone
    assert eng.cancel("never-existed") is False
    # the id is free again after cancel (dedupe map must not leak)
    eng.submit("retry", request_id="doomed")


def test_batch_failure_answers_requests_not_worker():
    pipe = FakePipeline(fail=True)
    eng = _engine(pipeline=pipe)
    eng.start()
    try:
        req = eng.submit("a")
        assert req.wait(timeout=10)
        assert req.state == MM_FAILED
        assert "tower exploded" in req.error
        # the worker thread survived the batch failure
        pipe.fail = False
        ok = eng.submit("b")
        assert ok.wait(timeout=10) and ok.state == MM_FINISHED
    finally:
        eng.stop()


def test_stop_cancels_queued_requests():
    eng = _engine()
    req = eng.submit("never served")
    eng.stop()
    assert req.state == MM_CANCELLED
    assert req.error == "engine stopped"


def test_warmup_runs_max_batch_and_stats_record_it():
    pipe = FakePipeline()
    eng = _engine(pipeline=pipe, max_batch=3)
    dt = eng.warmup()
    assert dt >= 0
    assert pipe.batches == [["warmup"] * 3]
    stats = eng.stats()
    assert stats["engine_type"] == "embedding"
    assert stats["warmup_s"] == dt
    assert stats["max_batch"] == 3
    assert stats["queue_depth"] == 0 and stats["in_flight"] == 0


# ---- the HTTP mapping ---------------------------------------------------

def _mm_generate(engine, req, timeout_s=10.0):
    from fengshen_tpu.api.main import _multimodal_generate
    return _multimodal_generate(engine, None, req, timeout_s)


def test_multimodal_generate_success_carries_engine_type():
    eng = _engine(cls=BatchImageEngine)
    eng.start()
    try:
        code, body = _mm_generate(eng, {"input_text": "一只猫"})
        assert code == 200
        assert body["result"] == {"result_for": "一只猫"}
        assert body["engine_type"] == "batch_image"
        assert body["request_id"]
    finally:
        eng.stop()


def test_multimodal_generate_backpressure_codes():
    eng = _engine(max_queue=1)  # no worker: deterministic backpressure
    eng.submit("filler", request_id="dup")
    code, body = _mm_generate(eng, {"input_text": "x",
                                    "request_id": "dup"})
    assert code == 409
    code, body = _mm_generate(eng, {"input_text": "x"})
    assert code == 429
    code, body = _mm_generate(eng, {"input_text": "  "})
    assert code == 422
    eng.begin_drain()
    code, body = _mm_generate(eng, {"input_text": "x"})
    assert code == 503 and body["reason"] == "draining"


def test_multimodal_generate_timeout_cancels_and_503s():
    eng = _engine()  # no worker: wait() can never be satisfied
    code, body = _mm_generate(eng, {"input_text": "x"}, timeout_s=0.05)
    assert code == 503 and "timed out" in body["error"]
    # the timed-out request was cancelled out of the queue
    assert eng.idle()


def test_multimodal_generate_failed_batch_maps_503():
    eng = _engine(pipeline=FakePipeline(fail=True))
    eng.start()
    try:
        code, body = _mm_generate(eng, {"input_text": "x"})
        assert code == 503
        assert "failed" in body["error"] and "tower exploded" in \
            body["error"]
    finally:
        eng.stop()


# ---- server dispatch (stdlib always; fastapi when installed) ------------

def _stdlib_server(engine, task):
    from fengshen_tpu.api.main import (PipelineConfig, ServerConfig,
                                       build_stdlib_server)
    server = build_stdlib_server(
        ServerConfig(host="127.0.0.1", port=0, engine=engine.engine_type),
        PipelineConfig(task=task), pipeline=engine.pipeline,
        engine=engine)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    return server, server.server_address[1]


def test_stdlib_server_dispatches_multimodal_engine():
    import urllib.error
    import urllib.request

    eng = _engine(cls=EmbeddingEngine)
    eng.start()
    server, port = _stdlib_server(eng, "embedding")
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/api/embedding",
            data=json.dumps({"input_text": "测试"}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as r:
            out = json.loads(r.read())
        assert out["engine_type"] == "embedding"
        assert out["result"] == {"result_for": "测试"}
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/stats", timeout=10) as r:
            stats = json.loads(r.read())
        assert stats["engine_type"] == "embedding"
        assert stats["requests_total"] >= 1
        bad = urllib.request.Request(
            f"http://127.0.0.1:{port}/api/embedding",
            data=json.dumps({"input_text": "  "}).encode(),
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(bad, timeout=30)
        assert exc.value.code == 422
    finally:
        server.shutdown()
        eng.stop()


def test_fastapi_app_dispatches_multimodal_engine():
    pytest.importorskip("fastapi")
    from fastapi.testclient import TestClient

    from fengshen_tpu.api.main import (PipelineConfig, ServerConfig,
                                       build_app)

    eng = _engine(cls=BatchImageEngine)
    eng.start()
    app = build_app(PipelineConfig(task="image_generation"),
                    pipeline=eng.pipeline,
                    server_cfg=ServerConfig(engine="batch_image"),
                    engine=eng)
    try:
        client = TestClient(app)
        r = client.post("/api/image_generation",
                        json={"input_text": "一只猫"})
        assert r.status_code == 200
        assert r.json()["engine_type"] == "batch_image"
        stats = client.get("/stats").json()
        assert stats["engine_type"] == "batch_image"
    finally:
        eng.stop()


def test_server_config_accepts_multimodal_engine_names():
    from fengshen_tpu.api.main import ServerConfig
    for name in ("simple", "continuous", "batch_image", "embedding"):
        ServerConfig(engine=name)
    with pytest.raises(ValueError, match="batch_image"):
        ServerConfig(engine="micro")


# ---- real towers end-to-end (pipeline → engine → stdlib HTTP) -----------

def test_embedding_tower_serves_end_to_end():
    import urllib.request

    from fengshen_tpu.pipelines.embedding import Pipeline

    pipe = Pipeline(small_test=True, seed=0)
    eng = EmbeddingEngine(pipe, max_batch=2, gather_ms=2.0)
    eng.warmup()
    eng.start()
    server, port = _stdlib_server(eng, "embedding")
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/api/embedding",
            data=json.dumps({"input_text": "今天天气真好"}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as r:
            out = json.loads(r.read())
        assert out["engine_type"] == "embedding"
        emb = out["result"]["embedding"]
        assert len(emb) == out["result"]["dim"] > 0
        # the tower L2-normalizes (CLIP contract)
        assert abs(sum(x * x for x in emb) - 1.0) < 1e-3
    finally:
        server.shutdown()
        eng.stop()


def test_batch_image_tower_serves_end_to_end():
    import base64
    import urllib.request

    from fengshen_tpu.pipelines.image_generation import Pipeline

    pipe = Pipeline(small_test=True, seed=0)
    eng = BatchImageEngine(pipe, max_batch=2, gather_ms=2.0)
    eng.warmup()
    eng.start()
    server, port = _stdlib_server(eng, "image_generation")
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/api/image_generation",
            data=json.dumps({"input_text": "一只橘猫"}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=300) as r:
            out = json.loads(r.read())
        assert out["engine_type"] == "batch_image"
        result = out["result"]
        assert result["dtype"] == "uint8"
        h, w, c = result["shape"]
        raw = base64.b64decode(result["image_b64"])
        assert len(raw) == h * w * c and c == 3
    finally:
        server.shutdown()
        eng.stop()


# ---- benchdiff row identity ---------------------------------------------

def test_benchdiff_engine_type_rows_incomparable():
    """`engine_type` is part of BENCH row identity: a batch_image round
    never diffs against an embedding round of the same metric name
    (same contract as offload placement / kernel dispatch / drills);
    same-engine rounds still diff honestly."""
    from fengshen_tpu.observability.benchdiff import diff_rounds

    rounds = [
        (1, "BENCH_r01.json", {"rc": 0, "parsed": [
            {"metric": "serving_mm_requests_per_sec", "value": 70.0,
             "unit": "requests/s", "vs_baseline": 1.3,
             "engine_type": "batch_image"}]}),
        (2, "BENCH_r02.json", {"rc": 0, "parsed": [
            {"metric": "serving_mm_requests_per_sec", "value": 2200.0,
             "unit": "requests/s", "vs_baseline": 2.9,
             "engine_type": "embedding"}]}),
        (3, "BENCH_r03.json", {"rc": 0, "parsed": [
            {"metric": "serving_mm_requests_per_sec", "value": 1100.0,
             "unit": "requests/s", "vs_baseline": 1.5,
             "engine_type": "embedding"}]}),
    ]
    report = diff_rounds(rounds)
    statuses = {(c["round"], c["status"])
                for c in report["comparisons"]}
    assert (2, "incomparable") in statuses   # engine type changed
    assert (3, "regression") in statuses     # embedding vs embedding


# ---- `make serve-bench-multimodal` harness smoke ------------------------

def test_serve_bench_multimodal_emits_engine_rows(monkeypatch):
    """The real towers (small-test Taiyi-SD + Taiyi-CLIP) through the
    real engines: one BENCH-schema row per engine type, each carrying
    the `engine_type` benchdiff folds into the row identity."""
    from fengshen_tpu.serving import bench

    for key in list(os.environ):
        if key.startswith(("SERVE_BENCH_", "BENCH_DEGRADED")):
            monkeypatch.delenv(key)
    monkeypatch.setenv("SERVE_BENCH_MODE", "multimodal")
    monkeypatch.setenv("SERVE_BENCH_REQUESTS", "2")
    monkeypatch.setenv("SERVE_BENCH_MAX_BATCH", "2")
    out = io.StringIO()
    with redirect_stdout(out):
        bench.main()
    rows = [json.loads(l) for l in out.getvalue().splitlines()
            if l.startswith("{")]
    by_type = {row["engine_type"]: row for row in rows}
    assert set(by_type) == {"batch_image", "embedding"}
    for engine_type, row in by_type.items():
        assert set(row) >= {"metric", "value", "unit", "vs_baseline",
                            "mode", "engine_type"}
        assert row["metric"] == \
            f"serving_{engine_type}_requests_per_sec"
        assert row["unit"] == "requests/s"
        assert row["mode"] == "multimodal"
        assert row["value"] > 0
        assert row["vs_baseline"] > 0
