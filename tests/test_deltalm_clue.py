"""DeltaLM + CLUE harness tests."""

import pytest
import json

import jax
import jax.numpy as jnp
import numpy as np

pytestmark = pytest.mark.slow  # full-fit/e2e lane: run with -m slow or no -m filter


def test_deltalm_forward_and_causality():
    from fengshen_tpu.models.deltalm import (DeltaLMConfig,
                                             DeltaLMForConditionalGeneration)
    cfg = DeltaLMConfig.small_test_config(dtype="float32")
    model = DeltaLMForConditionalGeneration(cfg)
    enc = jnp.asarray(np.random.RandomState(0).randint(3, 120, (2, 8)),
                      jnp.int32)
    dec = jnp.asarray(np.random.RandomState(1).randint(3, 120, (2, 6)),
                      jnp.int32)
    params = model.init(jax.random.PRNGKey(0), enc, dec)["params"]
    out = model.apply({"params": params}, enc, dec)
    assert out.shape == (2, 6, 128)
    # decoder causality with the interleaved layers
    dec2 = dec.at[:, -1].set(99)
    out2 = model.apply({"params": params}, enc, dec2)
    np.testing.assert_allclose(np.asarray(out[:, :-1]),
                               np.asarray(out2[:, :-1]), atol=1e-5)
    # interleaved structure: two FFN sublayers per decoder block
    layer = params["decoder_layer_0"]
    assert {"fc1", "fc2", "fc3", "fc4"} <= set(layer)


def test_clue_harness_with_fake_pipeline(tmp_path):
    from fengshen_tpu.examples.clue1_1.evaluate_clue import (
        evaluate_classification, evaluate_unimc, load_clue_jsonl)
    p = tmp_path / "dev.json"
    with open(p, "w") as f:
        f.write(json.dumps({"sentence1": "a", "sentence2": "b",
                            "label": 1}) + "\n")
        f.write(json.dumps({"sentence1": "c", "sentence2": "d",
                            "label": 0}) + "\n")
    rows = load_clue_jsonl(str(p))

    class FakePipe:
        def __call__(self, a, b=None):
            return {"label": 1, "score": 0.9}

    acc = evaluate_classification(FakePipe(), rows,
                                  ("sentence1", "sentence2"))
    assert acc == 0.5

    class FakeUniMC:
        def predict(self, data):
            return [1] * len(data)

    acc2 = evaluate_unimc(FakeUniMC(), rows, ["不同", "相同"],
                          ("sentence1", "sentence2"))
    assert acc2 == 0.5
